"""ONNX interop: protobuf codec round-trip, export→import numeric equality.

Mirrors the reference's tests/onnx round-trip strategy (hetu→onnx→TF and
back, tests/onnx/test_nodes.py) with the oracle being the original jax
function itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_tpu.interop import (
    ModelProto, export_fn, export_module, import_model, load_model, save_model,
)
from hetu_tpu.interop import onnx_pb as pb


def roundtrip(fn, *args, atol=1e-5):
    proto = export_fn(fn, *args)
    data = proto.encode()
    fn2, params = import_model(data)
    want = fn(*args)
    got = fn2(params, *args)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a, np.float32),
                                                np.asarray(b, np.float32),
                                                atol=atol, rtol=1e-4),
        want, got)
    return proto


class TestCodec:
    def test_tensor_roundtrip(self):
        for arr in [np.random.randn(3, 4).astype(np.float32),
                    np.arange(6, dtype=np.int64).reshape(2, 3),
                    np.array(True)]:
            t = pb.tensor_from_numpy("x", arr)
            back = pb.tensor_to_numpy(pb.TensorProto.decode(t.encode()))
            np.testing.assert_array_equal(arr, back)

    def test_model_roundtrip(self):
        node = pb.NodeProto(op_type="Add", inputs=("a", "b"), outputs=("c",),
                            attributes=(pb.AttributeProto.make("axis", 1),
                                        pb.AttributeProto.make("f", 2.5),
                                        pb.AttributeProto.make("name", "hi"),
                                        pb.AttributeProto.make("ints", [1, 2])))
        graph = pb.GraphProto(
            nodes=(node,),
            initializers=(pb.tensor_from_numpy("b", np.ones((2,), np.float32)),),
            inputs=(pb.ValueInfoProto("a", pb.FLOAT, (2,)),),
            outputs=(pb.ValueInfoProto("c", pb.FLOAT, (2,)),))
        m = pb.ModelProto(graph=graph)
        m2 = ModelProto.decode(m.encode())
        assert m2.graph.nodes[0].op_type == "Add"
        assert m2.graph.nodes[0].attr("axis") == 1
        assert m2.graph.nodes[0].attr("f") == 2.5
        assert m2.graph.nodes[0].attr("name") == "hi"
        assert m2.graph.nodes[0].attr("ints") == [1, 2]
        assert m2.graph.inputs[0].shape == (2,)
        np.testing.assert_array_equal(
            pb.tensor_to_numpy(m2.graph.initializers[0]), np.ones((2,)))


class TestExportImport:
    def test_elementwise_chain(self):
        x = jnp.asarray(np.random.randn(4, 5), jnp.float32)
        roundtrip(lambda x: jnp.tanh(x) * 2.0 + jnp.exp(-x * x), x)

    def test_matmul_bias_relu(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((4,)), jnp.float32)
        roundtrip(lambda x: jax.nn.relu(x @ w + b), x)

    def test_reductions_softmax(self):
        x = jnp.asarray(np.random.randn(3, 7), jnp.float32)
        roundtrip(lambda x: jax.nn.softmax(x, axis=-1).sum(axis=0), x)
        roundtrip(lambda x: x.max(axis=1) - x.min(axis=1), x)
        roundtrip(lambda x: jnp.mean(x * x, axis=-1, keepdims=True), x)

    def test_shape_ops(self):
        x = jnp.asarray(np.random.randn(2, 3, 4), jnp.float32)
        roundtrip(lambda x: jnp.transpose(x, (2, 0, 1)).reshape(4, 6), x)
        roundtrip(lambda x: jnp.concatenate([x, x], axis=1), x)
        roundtrip(lambda x: x[:, 1:3, ::2], x)
        roundtrip(lambda x: jnp.pad(x, ((0, 0), (1, 1), (2, 0))), x)
        roundtrip(lambda x: jnp.flip(x, axis=2), x)

    def test_comparisons_where(self):
        x = jnp.asarray(np.random.randn(5, 5), jnp.float32)
        roundtrip(lambda x: jnp.where(x > 0, x, 0.1 * x), x)

    def test_cast_clamp(self):
        x = jnp.asarray(np.random.randn(6), jnp.float32)
        roundtrip(lambda x: jnp.clip(x, -0.5, 0.5).astype(jnp.float32), x)

    def test_gather_embedding(self):
        table = jnp.asarray(np.random.randn(10, 4), jnp.float32)
        ids = jnp.asarray([[1, 3], [5, 7]], jnp.int32)
        roundtrip(lambda ids: jnp.take(table, ids, axis=0), ids)

    def test_layernorm_pattern(self):
        from hetu_tpu.ops import nn as hnn
        x = jnp.asarray(np.random.randn(4, 16), jnp.float32)
        g = jnp.ones((16,), jnp.float32)
        b = jnp.zeros((16,), jnp.float32)
        roundtrip(lambda x: hnn.layer_norm(x, g, b), x)

    def test_argmax_cumsum(self):
        x = jnp.asarray(np.random.randn(3, 9), jnp.float32)
        roundtrip(lambda x: jnp.argmax(x, axis=1).astype(jnp.int32), x)
        roundtrip(lambda x: jnp.cumsum(x, axis=1), x)

    def test_dynamic_slice(self):
        x = jnp.asarray(np.random.randn(4, 8), jnp.float32)
        i = jnp.asarray(2, jnp.int32)
        f = lambda x, i: jax.lax.dynamic_slice(x, (0, i), (4, 3))
        proto = export_fn(f, x, i)
        fn, params = import_model(proto.encode())
        np.testing.assert_allclose(np.asarray(f(x, i)),
                                   np.asarray(fn(params, x, i)), atol=1e-6)
        # out-of-bounds start: jax clamps; export must match
        big = jnp.asarray(7, jnp.int32)
        np.testing.assert_allclose(np.asarray(f(x, big)),
                                   np.asarray(fn(params, x, big)), atol=1e-6)
        # and stays jittable
        jitted = jax.jit(lambda p, a, b: fn(p, a, b))
        np.testing.assert_allclose(np.asarray(jitted(params, x, i)),
                                   np.asarray(f(x, i)), atol=1e-6)

    def test_rem_sign_and_is_finite(self):
        x = jnp.asarray([-5.0, 5.0, -7.5], jnp.float32)
        y = jnp.asarray([3.0, -3.0, 2.0], jnp.float32)
        roundtrip(lambda x, y: jax.lax.rem(x, y), x, y)
        z = jnp.asarray([1.0, jnp.inf, -jnp.inf, jnp.nan], jnp.float32)
        proto = export_fn(lambda z: jnp.isfinite(z), z)
        fn, params = import_model(proto.encode())
        np.testing.assert_array_equal(np.asarray(jnp.isfinite(z)),
                                      np.asarray(fn(params, z)))

    def test_avg_pool_padded_external_model(self):
        """External-style AveragePool with pads and default count_include_pad=0."""
        node = pb.NodeProto(op_type="AveragePool", inputs=("x",), outputs=("y",),
                            attributes=(pb.AttributeProto.make("kernel_shape", [2, 2]),
                                        pb.AttributeProto.make("pads", [1, 1, 0, 0])))
        graph = pb.GraphProto(nodes=(node,),
                              inputs=(pb.ValueInfoProto("x", pb.FLOAT, (1, 1, 3, 3)),),
                              outputs=(pb.ValueInfoProto("y", pb.FLOAT, (1, 1, 3, 3)),))
        fn, params = import_model(pb.ModelProto(graph=graph).encode())
        x = jnp.ones((1, 1, 3, 3), jnp.float32)
        y = np.asarray(fn(params, x))
        # every window must average to 1.0 when divisor excludes padding
        np.testing.assert_allclose(y, np.ones_like(y), atol=1e-6)
        # default strides must be 1 (not kernel_shape)
        assert y.shape == (1, 1, 3, 3)

    def test_dot_general_einsum_path(self):
        rng = np.random.default_rng(1)
        a = jnp.asarray(rng.standard_normal((2, 3, 4)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((2, 4, 5)), jnp.float32)
        roundtrip(lambda a, b: jnp.einsum("bij,bjk->bik", a, b), a, b)
        # contraction over a middle dim forces the Einsum fallback
        c = jnp.asarray(rng.standard_normal((4, 5, 2)), jnp.float32)
        roundtrip(lambda a, c: jnp.einsum("bij,jkb->bik", a, c), a, c)
        # rank-3 rhs with NO batch dims: jax puts lhs free dims first,
        # ONNX MatMul would broadcast — must take the Einsum path
        d = jnp.asarray(rng.standard_normal((2, 3)), jnp.float32)
        e = jnp.asarray(rng.standard_normal((4, 3, 5)), jnp.float32)
        roundtrip(lambda d, e: jnp.dot(d, e), d, e)


class TestModels:
    def test_mlp_module(self):
        from hetu_tpu.core import set_random_seed
        from hetu_tpu.layers import Linear, Sequential
        from hetu_tpu.layers.base import Lambda

        set_random_seed(0)
        model = Sequential(Linear(8, 16), Lambda(jax.nn.relu), Linear(16, 2))
        x = jnp.asarray(np.random.randn(4, 8), jnp.float32)
        proto = export_module(model, x)
        fn, params = import_model(proto.encode())
        np.testing.assert_allclose(np.asarray(model(x)),
                                   np.asarray(fn(params, x)),
                                   atol=1e-5, rtol=1e-4)

    def test_cnn_conv_pool(self):
        from hetu_tpu.ops import nn as hnn
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((2, 8, 8, 3)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((3, 3, 3, 4)) * 0.1, jnp.float32)

        def f(x):
            h = hnn.conv2d(x, w, stride=1, padding="SAME")
            h = jax.nn.relu(h)
            h = hnn.max_pool2d(h, window=2)
            return hnn.avg_pool2d(h, window=2)

        roundtrip(f, x, atol=1e-4)

    def test_save_load_file(self, tmp_path):
        x = jnp.asarray(np.random.randn(3, 3), jnp.float32)
        proto = export_fn(lambda x: jnp.tanh(x) @ jnp.eye(3), x)
        p = tmp_path / "m.onnx"
        save_model(proto, str(p))
        fn, params = load_model(str(p))
        np.testing.assert_allclose(np.asarray(jnp.tanh(x) @ jnp.eye(3)),
                                   np.asarray(fn(params, x)), atol=1e-5)

    def test_jit_imported(self):
        """Imported fn must be jittable (pure jnp interpreter)."""
        x = jnp.asarray(np.random.randn(4, 4), jnp.float32)
        proto = export_fn(lambda x: jax.nn.softmax(x @ x.T), x)
        fn, params = import_model(proto.encode())
        jitted = jax.jit(lambda p, x: fn(p, x))
        np.testing.assert_allclose(np.asarray(jitted(params, x)),
                                   np.asarray(jax.nn.softmax(x @ x.T)),
                                   atol=1e-5, rtol=1e-4)


class TestScanExport:
    # slow tier (r5 re-tier pass 2): reverse-scan roundtrip keeps the
    # scan-export path fast; LSTM is the heavier twin
    @pytest.mark.slow
    def test_lstm_roundtrip(self):
        from hetu_tpu.core import set_random_seed
        from hetu_tpu.models import LSTMCell, RNN
        set_random_seed(0)
        r = RNN(LSTMCell(4, 8))
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 4)),
                        jnp.float32)
        proto = export_module(r, x, apply=lambda m, xx: m(xx)[0])
        fn, params = import_model(proto)
        np.testing.assert_allclose(np.asarray(fn(params, x)),
                                   np.asarray(r(x)[0]),
                                   rtol=1e-4, atol=1e-5)

    def test_reverse_scan_roundtrip(self):
        from hetu_tpu.core import set_random_seed
        from hetu_tpu.models import RNN, RNNCell
        set_random_seed(1)
        r = RNN(RNNCell(4, 6), reverse=True)
        x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 5, 4)),
                        jnp.float32)
        proto = export_module(r, x, apply=lambda m, xx: m(xx)[0])
        fn, params = import_model(proto)
        np.testing.assert_allclose(np.asarray(fn(params, x)),
                                   np.asarray(r(x)[0]),
                                   rtol=1e-4, atol=1e-5)

    def test_unroll_limit(self):
        import pytest as _pytest
        from jax import lax

        def f(x):
            return lax.scan(lambda c, t: (c + t, c), x[0], x)[0]

        x = jnp.zeros((1000, 2), jnp.float32)
        with _pytest.raises(NotImplementedError):
            export_fn(f, x)

    def test_scalar_initializer_rank_preserved(self):
        from hetu_tpu.interop import onnx_pb as pb
        t = pb.tensor_from_numpy("s", np.asarray(3, np.int64))
        assert t.dims == ()
        rt = pb.tensor_to_numpy(pb.TensorProto.decode(t.encode()))
        assert rt.shape == () and int(rt) == 3

    def test_split_roundtrip(self):
        def f(x):
            a, b, c = jnp.split(x, [2, 5], axis=1)
            return a * 1.0 + a.sum() * 0, b.sum(), c.sum()

        x = jnp.asarray(np.random.default_rng(2).normal(size=(3, 8)),
                        jnp.float32)
        proto = export_fn(f, x)
        fn, params = import_model(proto)
        for got, want in zip(fn(params, x), f(x)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-6)

    def test_zero_length_scan_rejected(self):
        import pytest as _pytest
        from jax import lax

        def f(x):
            return lax.scan(lambda c, t: (c + t, c), x.sum(0), x)[1]

        with _pytest.raises(NotImplementedError):
            export_fn(f, jnp.zeros((0, 3), jnp.float32))


# slow tier (r5 re-tier pass 2): MLP/CNN roundtrips stay fast; the external-consumer BERT test is slow-tier too
@pytest.mark.slow
def test_bert_roundtrip():
    """Full BERT-for-pretraining forward exports and re-imports with
    matching numerics — transformer coverage beyond the reference's
    cnn/dnn/rnn round-trips (tests/onnx/)."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.models import BertForPreTraining, bert_base

    set_random_seed(0)
    cfg = bert_base(num_layers=2, hidden_size=32, num_heads=2,
                    vocab_size=100, max_position_embeddings=16)
    model = BertForPreTraining(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 100, (2, 8)),
                      jnp.int32)
    tt = jnp.zeros((2, 8), jnp.int32)

    def fwd(ids, tt):
        mlm, _nsp = model(ids, tt, None)
        return mlm

    roundtrip(fwd, ids, tt, atol=2e-4)
