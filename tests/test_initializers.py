"""Initializer tests: statistical properties + seqnum reproducibility
(reference tests/test_gpu_initializers.py compares curand draws to scipy
moments; here the oracle is the same — sample statistics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_tpu.core import set_random_seed
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import (
    constant, he_normal, he_uniform, lecun_normal, lecun_uniform, normal,
    ones, truncated_normal, uniform, xavier_normal, xavier_uniform, zeros,
)

SHAPE = (512, 256)


def test_constant_family():
    k = jax.random.key(0)
    np.testing.assert_array_equal(np.asarray(zeros(k, SHAPE)), 0.0)
    np.testing.assert_array_equal(np.asarray(ones(k, SHAPE)), 1.0)
    np.testing.assert_array_equal(np.asarray(constant(3.5)(k, SHAPE)), 3.5)


def test_uniform_bounds_and_mean():
    x = np.asarray(uniform(-0.2, 0.6)(jax.random.key(1), SHAPE))
    assert x.min() >= -0.2 and x.max() <= 0.6
    assert abs(x.mean() - 0.2) < 0.01


def test_normal_moments():
    x = np.asarray(normal(1.0, 0.5)(jax.random.key(2), SHAPE))
    assert abs(x.mean() - 1.0) < 0.01
    assert abs(x.std() - 0.5) < 0.01


def test_truncated_normal_bounds():
    x = np.asarray(truncated_normal(0.0, 1.0)(jax.random.key(3), SHAPE))
    # truncation at 2 sigma
    assert np.abs(x).max() <= 2.0 + 1e-5
    assert abs(x.mean()) < 0.02


@pytest.mark.parametrize("init,var_fn", [
    (xavier_uniform, lambda fi, fo: 2.0 / (fi + fo)),
    (xavier_normal, lambda fi, fo: 2.0 / (fi + fo)),
    (he_uniform, lambda fi, fo: 2.0 / fi),
    (he_normal, lambda fi, fo: 2.0 / fi),
    (lecun_uniform, lambda fi, fo: 1.0 / fi),
    (lecun_normal, lambda fi, fo: 1.0 / fi),
])
def test_scaled_variance(init, var_fn):
    fi, fo = SHAPE
    x = np.asarray(init()(jax.random.key(4), SHAPE))
    want = var_fn(fi, fo)
    assert abs(x.var() / want - 1.0) < 0.08, (x.var(), want)
    assert abs(x.mean()) < 0.01


def test_fan_computation_conv():
    # conv kernel [kh, kw, cin, cout]: fan_in = kh*kw*cin
    x = np.asarray(he_normal()(jax.random.key(5), (3, 3, 16, 32)))
    want = 2.0 / (3 * 3 * 16)
    assert abs(x.var() / want - 1.0) < 0.15


def test_seed_seqnum_reproducibility():
    """Same (seed, seqnum) stream -> identical draws — the property the
    reference checkpoints via random.py:31 (seed, seqnum)."""
    set_random_seed(123)
    a1 = normal()(next_key(), SHAPE)
    a2 = normal()(next_key(), SHAPE)
    set_random_seed(123)
    b1 = normal()(next_key(), SHAPE)
    b2 = normal()(next_key(), SHAPE)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(b1))
    np.testing.assert_array_equal(np.asarray(a2), np.asarray(b2))
    assert not np.array_equal(np.asarray(a1), np.asarray(a2))
