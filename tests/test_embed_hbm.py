"""HBMCachedEmbedding: hot rows staged in device HBM over the host store.

Oracle: with pull_bound=0 (strict freshness) the HBM-cached layer must
train BIT-COMPATIBLY with StagedHostEmbedding on the same data — the cache
is a transport optimization, not a semantics change.  Plus cache-behavior
invariants: warm steps refresh nothing, pushes staleness-invalidate,
eviction under pressure, thrash detection.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_tpu.core import set_random_seed
from hetu_tpu.core.module import Module
from hetu_tpu.embed import HBMCachedEmbedding, StagedHostEmbedding
from hetu_tpu.exec import Trainer
from hetu_tpu.layers import Linear
from hetu_tpu.ops import binary_cross_entropy_with_logits
from hetu_tpu.optim import AdamOptimizer


class Tiny(Module):
    def __init__(self, emb):
        self.emb = emb
        self.head = Linear(4 * 3, 1)

    def loss(self, sp, y):
        e = self.emb(sp).reshape(sp.shape[0], -1)
        return binary_cross_entropy_with_logits(self.head(e)[:, 0], y).mean()


def _data(n=64, vocab=50, fields=3, seed=0):
    rng = np.random.default_rng(seed)
    # zipf-ish skew so the cache has hot rows
    sp = np.minimum(rng.zipf(1.5, (n, fields)) - 1, vocab - 1).astype(np.int32)
    y = (sp.sum(1) % 2).astype(np.float32)
    return sp, y


def _train(emb, steps=12, batch=16):
    set_random_seed(0)
    model = Tiny(emb)
    tr = Trainer(model, AdamOptimizer(1e-2),
                 lambda m, b, k: (m.loss(b["sp"], b["y"]), {}))
    sp, y = _data()
    losses = []
    for s in range(steps):
        lo = (s * batch) % (len(y) - batch)
        b = {"sp": jnp.asarray(sp[lo:lo + batch]),
             "y": jnp.asarray(y[lo:lo + batch])}
        for m in tr.staged_modules():
            m.stage(b["sp"])
        losses.append(float(tr.step(b)["loss"]))
    return losses, tr


def test_matches_staged_oracle():
    """Strict-freshness HBM cache == plain staged path, step by step."""
    set_random_seed(0)
    l_ref, tr_ref = _train(StagedHostEmbedding(50, 4, optimizer="adagrad",
                                               lr=0.05, seed=7))
    set_random_seed(0)
    l_hbm, tr_hbm = _train(HBMCachedEmbedding(50, 4, optimizer="adagrad",
                                              lr=0.05, seed=7,
                                              hbm_capacity=64,
                                              hbm_pull_bound=0))
    np.testing.assert_allclose(l_hbm, l_ref, rtol=1e-5)
    # and the host tables ended identical
    ids = np.arange(50)
    np.testing.assert_allclose(
        tr_hbm.state.model.emb.table.pull(ids),
        tr_ref.state.model.emb.table.pull(ids), rtol=1e-5)
    assert l_hbm[-1] < l_hbm[0]


def test_warm_steps_refresh_nothing():
    """Same batch twice without a push between: the second stage must not
    touch the host store (the transport saving the HBM cache exists for)."""
    emb = HBMCachedEmbedding(50, 4, hbm_capacity=32, hbm_pull_bound=0)
    ids = jnp.asarray([[1, 2, 3], [4, 1, 2]])
    emb.stage(ids)
    first = np.asarray(emb(ids))
    pulls_before = emb.table.pull  # wrap to count
    calls = []
    emb.table.pull = lambda k: (calls.append(len(np.asarray(k))),
                                pulls_before(k))[1]
    emb._handle.ids = None  # simulate eval-style reuse (no push)
    emb.stage(ids)
    assert calls == []  # fully warm: zero host pulls
    np.testing.assert_array_equal(np.asarray(emb(ids)), first)
    emb.table.pull = pulls_before


def test_push_invalidates_with_bound_zero():
    """After a gradient push, pull_bound=0 forces a refresh of exactly the
    pushed rows on the next stage."""
    emb = HBMCachedEmbedding(50, 4, optimizer="sgd", lr=1.0,
                             hbm_capacity=32, hbm_pull_bound=0)
    ids = jnp.asarray([[5, 6]])
    emb.stage(ids)
    before = np.asarray(emb(ids)).copy()
    g = np.ones(tuple(ids.shape) + (4,), np.float32)  # grad 1 on both rows
    emb.push_grads(jnp.asarray(g))
    emb.stage(ids)  # must re-pull rows 5,6 (server applied -1.0 * lr)
    after = np.asarray(emb(ids))
    np.testing.assert_allclose(after, before - 1.0, rtol=1e-6)


def test_stale_reuse_with_loose_bound():
    """pull_bound=k keeps serving the device copy for up to k pushes —
    HET's bounded staleness."""
    emb = HBMCachedEmbedding(50, 4, optimizer="sgd", lr=1.0,
                             hbm_capacity=32, hbm_pull_bound=2)
    ids = jnp.asarray([[9]])
    emb.stage(ids)
    v0 = np.asarray(emb(ids)).copy()
    for _ in range(2):  # two pushes: staleness 1, 2 <= bound
        emb.stage(ids)
        g = np.ones(tuple(ids.shape) + (4,), np.float32)
        emb.push_grads(jnp.asarray(g))
    emb.stage(ids)
    np.testing.assert_array_equal(np.asarray(emb(ids)), v0)  # still cached
    # third push exceeds the bound -> refresh picks up all three updates
    emb.stage(ids)
    g = np.ones(tuple(ids.shape) + (4,), np.float32)
    emb.push_grads(jnp.asarray(g))
    emb.stage(ids)
    np.testing.assert_allclose(np.asarray(emb(ids)), v0 - 3.0, rtol=1e-6)


def test_eviction_and_thrash():
    emb = HBMCachedEmbedding(100, 4, hbm_capacity=4)
    emb.stage(jnp.asarray([[0, 1, 2, 3]]))
    emb._handle.ids = None
    emb.stage(jnp.asarray([[4, 5]]))  # evicts two LRU rows
    assert emb.hit_stats()["resident"] == 4
    assert emb._handle.slot_of[4] >= 0 and emb._handle.slot_of[5] >= 0


def test_overflow_falls_back_to_host_path():
    """A batch touching more unique rows than hbm_capacity degrades to the
    host path for the overflow rows (journaled) instead of killing the
    step — and still serves every row's correct value."""
    from hetu_tpu.obs import journal as obs_journal

    emb = HBMCachedEmbedding(100, 4, hbm_capacity=4, init_scale=1.0)
    j = obs_journal.EventJournal()
    with obs_journal.use(j):
        ids = jnp.asarray([[1, 2, 3, 4, 5, 6]])  # 6 unique > 4 slots
        emb.stage(ids)
        got = np.asarray(emb(ids))[0]
    np.testing.assert_allclose(
        got, emb.table.pull(np.arange(1, 7)), rtol=1e-6)
    st = emb.hit_stats()
    assert st["overflows"] == 2 and st["resident"] == 4
    ev = [e for e in j.events if e["kind"] == "hbm_overflow"]
    assert len(ev) == 1 and ev[0]["overflow"] == 2 \
        and ev[0]["capacity"] == 4 and ev[0]["batch_rows"] == 6
    # gradients still reach the host engine for ALL rows, incl. overflow
    emb.push_grads(jnp.ones(tuple(ids.shape) + (4,), jnp.float32))
    # and the next small batch is back on the pure-HBM path
    emb.stage(jnp.asarray([[1, 2]]))
    assert np.asarray(emb.rows).max() == 0.0  # leaf back to zeros


def test_overflow_trains_like_staged_oracle():
    """Regression for the fallback math: a training run whose EVERY batch
    overflows (capacity 2) must still match the plain staged path exactly
    under strict freshness — the overflow rows are just staged transfers."""
    set_random_seed(0)
    l_ref, tr_ref = _train(StagedHostEmbedding(50, 4, optimizer="adagrad",
                                               lr=0.05, seed=7))
    set_random_seed(0)
    l_hbm, tr_hbm = _train(HBMCachedEmbedding(50, 4, optimizer="adagrad",
                                              lr=0.05, seed=7,
                                              hbm_capacity=2,
                                              hbm_pull_bound=0))
    np.testing.assert_allclose(l_hbm, l_ref, rtol=1e-5)
    ids = np.arange(50)
    np.testing.assert_allclose(
        tr_hbm.state.model.emb.table.pull(ids),
        tr_ref.state.model.emb.table.pull(ids), rtol=1e-5)


def test_ctr_config_hbm_path():
    from hetu_tpu.models import CTRConfig, WideDeep

    set_random_seed(0)
    cfg = CTRConfig(vocab=200, embed_dim=4, embedding="hbm",
                    cache_capacity=1024, host_optimizer="adagrad",
                    host_lr=0.05)
    model = WideDeep(cfg)
    tr = Trainer(model, AdamOptimizer(1e-3),
                 lambda m, b, k: m.loss(b["dense"], b["sparse"], b["label"]))
    rng = np.random.default_rng(0)
    b = {"dense": jnp.asarray(rng.normal(size=(16, 13)), jnp.float32),
         "sparse": jnp.asarray(rng.integers(0, 200, (16, 26)), jnp.int32),
         "label": jnp.asarray(rng.integers(0, 2, (16,)), jnp.float32)}
    for m in tr.staged_modules():
        m.stage(b["sparse"])
    l0 = float(tr.step(b)["loss"])
    for _ in range(10):
        for m in tr.staged_modules():
            m.stage(b["sparse"])
        m2 = tr.step(b)
    assert float(m2["loss"]) < l0


def test_partial_free_eviction_keeps_slots_distinct():
    """Regression: with SOME free slots but fewer than the misses, victim
    selection must not re-pick a free slot — two ids would share one cache
    row and one would silently serve the other's embedding."""
    emb = HBMCachedEmbedding(100, 4, hbm_capacity=4, init_scale=1.0)
    emb.stage(jnp.asarray([[0, 1, 2]]))  # slot 3 stays free
    emb._handle.ids = None
    ids2 = jnp.asarray([[4, 5, 6]])  # 3 misses, only 1 free slot
    emb.stage(ids2)
    slots = emb._handle.slot_of[[4, 5, 6]]
    assert len(set(slots.tolist())) == 3, f"slot collision: {slots}"
    np.testing.assert_allclose(np.asarray(emb(ids2))[0],
                               emb.table.pull(np.array([4, 5, 6])),
                               rtol=1e-6)
    # directory stayed consistent: resident ids' slots roundtrip
    h = emb._handle
    for s in range(4):
        if h.id_of[s] >= 0:
            assert h.slot_of[h.id_of[s]] == s


def test_prefetch_never_installs_pre_push_snapshot():
    """Regression: a prefetch issued BEFORE a gradient push must not be
    installed as a fresh copy of the pushed rows (it predates the server
    update) — strict freshness (pull_bound=0) has to re-pull them."""
    emb = HBMCachedEmbedding(50, 4, optimizer="sgd", lr=1.0,
                             cache_capacity=64,  # host cache => prefetcher
                             hbm_capacity=32, hbm_pull_bound=0)
    a = jnp.asarray([[1, 2]])
    emb.stage(a)
    before = np.asarray(emb(a)).copy()
    emb.prefetch(jnp.asarray([[1, 3]]))   # buffer snapshot: pre-push
    emb.push_grads(jnp.ones(tuple(a.shape) + (4,), jnp.float32))
    b = jnp.asarray([[1, 3]])
    emb.stage(b)                           # id 1 stale -> must re-pull
    got = np.asarray(emb(b))
    np.testing.assert_allclose(got[0, 0], before[0, 0] - 1.0, rtol=1e-6)
