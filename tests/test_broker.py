"""Elastic chip market tests (hetu_tpu/broker + the gang lend/rejoin
seam + fleet membership states + the diurnal loadgen satellite).

Tier-1: the Lease state machine, the diurnal trace determinism suite,
mid-flight fleet membership (a router never routes to warming or
reclaiming replicas, and a reclaiming replica DRAINS — in-flight
requests complete, never drop), the gang's save-at-lend zero-replay
contract (post-lend losses bitwise equal to an uninterrupted run), the
broker unit loop (hysteresis, sustain, cooldown, LIFO reclaim, the
min_train_world floor, dry-run parity, metrics, /broker), and the
seeded diurnal acceptance: a brokered fleet jointly beats BOTH static
splits on (SLO violations, training goodput), the whole episode replays
bitwise across same-seed runs, and the gang's loss trajectory matches
an uninterrupted run at equal total steps.
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from hetu_tpu import obs
from hetu_tpu.broker import (LEASE_STATES, BrokerConfig, CapacityBroker,
                             Lease, LeaseStateError, broker_families,
                             get_broker)
from hetu_tpu.broker import use as broker_use
from hetu_tpu.broker.episode import run_broker_episode
from hetu_tpu.core import set_random_seed
from hetu_tpu.exec import Trainer
from hetu_tpu.exec.gang import ElasticGang, GangError
from hetu_tpu.models import MLP
from hetu_tpu.models.gpt import GPT, GPTConfig
from hetu_tpu.obs import journal as obs_journal
from hetu_tpu.optim import SGDOptimizer
from hetu_tpu.ops import softmax_cross_entropy_sparse
from hetu_tpu.serve import ServingEngine, generate_diurnal_load
from hetu_tpu.serve.fleet.disagg import DisaggRouter
from hetu_tpu.serve.fleet.router import FleetRouter
from hetu_tpu.serve.loadgen import DEFAULT_DIURNAL_PHASES

pytestmark = pytest.mark.broker

CFG = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=64)


@pytest.fixture(scope="module")
def model():
    set_random_seed(0)
    return GPT(CFG)


class VClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def make_engine(model, clock, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("seed", 0)
    kw.setdefault("queue_depth", 64)
    return ServingEngine(model, clock=clock, **kw)


def drain(router, clock, max_steps: int = 5000) -> int:
    for i in range(max_steps):
        if router.idle:
            return i
        router.step()
        clock.t += 0.001
    raise AssertionError(f"not idle after {max_steps} ticks")


# ------------------------------------------------- lease state machine

class TestLease:
    def mk(self, **kw):
        kw.setdefault("lease_id", 0)
        kw.setdefault("chip", 3)
        kw.setdefault("from_role", "train")
        kw.setdefault("to_role", "serve")
        kw.setdefault("trigger", "slo_burn")
        kw.setdefault("plan_sha", "abc")
        kw.setdefault("generation", 2)
        return Lease(**kw)

    def test_happy_path(self):
        lease = self.mk()
        assert lease.state == "offered" and lease.active
        lease.advance("warming")
        lease.advance("serving", tick=5)
        assert lease.serving_tick == 5 and lease.active
        lease.advance("reclaiming")
        lease.advance("returned", tick=9)
        assert lease.returned_tick == 9 and not lease.active
        assert lease.state == LEASE_STATES[-1]

    def test_early_reclaim_from_warming(self):
        lease = self.mk()
        lease.advance("warming")
        lease.advance("reclaiming")  # pressure released mid-warm-up
        lease.advance("returned")
        assert lease.state == "returned"

    def test_invalid_transitions_raise(self):
        lease = self.mk()
        with pytest.raises(LeaseStateError):
            lease.advance("serving")  # offered cannot skip warming
        lease.advance("warming")
        with pytest.raises(LeaseStateError):
            lease.advance("offered")  # no going back
        lease.advance("serving")
        lease.advance("reclaiming")
        lease.advance("returned")
        for s in LEASE_STATES:
            with pytest.raises(LeaseStateError):
                lease.advance(s)  # returned is terminal
        with pytest.raises(LeaseStateError):
            self.mk().advance("not_a_state")

    def test_as_dict(self):
        d = self.mk().as_dict()
        assert d["lease_id"] == 0 and d["chip"] == 3
        assert d["from_role"] == "train" and d["to_role"] == "serve"
        assert d["state"] == "offered" and d["plan_sha"] == "abc"
        assert d["generation"] == 2


# ------------------------------------- satellite: diurnal load generator

class TestDiurnalLoad:
    def test_bitwise_determinism(self):
        a = generate_diurnal_load(7, 60, vocab=97)
        b = generate_diurnal_load(7, 60, vocab=97)
        assert a == b
        assert generate_diurnal_load(8, 60, vocab=97) != a

    def test_phase_walk_and_monotone_arrivals(self):
        trace = generate_diurnal_load(1, 80, vocab=97)
        names = [p["name"] for p in DEFAULT_DIURNAL_PHASES]
        seen = [it.phase for it in trace]
        # phases appear in spec order, contiguously
        assert [n for n in dict.fromkeys(seen)] == names
        ts = [it.submit_at for it in trace]
        assert all(b >= a for a, b in zip(ts, ts[1:]))

    def test_budget_split_is_exact(self):
        trace = generate_diurnal_load(2, 81, vocab=97)
        counts = {}
        for it in trace:
            counts[it.phase] = counts.get(it.phase, 0) + 1
        # shares .2/.2/.4/.2 of 81: floors 16/16/32/16 = 80, the one
        # leftover goes to the earliest phase
        assert counts == {"off_peak": 17, "ramp": 16, "peak": 32,
                          "decay": 16}
        assert sum(counts.values()) == 81

    def test_gap_follows_rate(self):
        trace = generate_diurnal_load(3, 400, vocab=97,
                                      peak_gap_s=0.01)
        by_phase = {}
        prev_t = 0.0
        for it in trace:
            by_phase.setdefault(it.phase, []).append(
                it.submit_at - prev_t)
            prev_t = it.submit_at
        # off-peak (rate .2) arrivals are ~5x sparser than peak (rate 1)
        assert np.mean(by_phase["off_peak"]) > \
            2.5 * np.mean(by_phase["peak"])

    def test_tenant_mix(self):
        tenants = [{"id": "interactive", "share": 0.7,
                    "deadline_s": 0.3},
                   {"id": "batch", "share": 0.3, "max_new": (4, 8)}]
        trace = generate_diurnal_load(4, 200, vocab=97,
                                      tenants=tenants)
        ids = [it.tenant for it in trace]
        assert set(ids) == {"interactive", "batch"}
        frac = ids.count("interactive") / len(ids)
        assert 0.55 < frac < 0.85  # seeded draw around the 0.7 share
        for it in trace:
            if it.tenant == "interactive":
                assert it.deadline_s == 0.3
            else:
                assert it.deadline_s is None
                assert 4 <= it.max_new_tokens <= 8

    def test_per_phase_tenant_override(self):
        phases = [{"name": "night", "rate": 0.2, "share": 0.5},
                  {"name": "day", "rate": 1.0, "share": 0.5,
                   "tenants": [{"id": "t0"}]}]
        trace = generate_diurnal_load(5, 40, vocab=97, phases=phases)
        for it in trace:
            if it.phase == "night":
                assert it.tenant is None  # no trace-wide mix to inherit
            else:
                assert it.tenant == "t0"

    def test_validation(self):
        with pytest.raises(ValueError, match="at least one phase"):
            generate_diurnal_load(0, 10, vocab=97, phases=[])
        with pytest.raises(ValueError, match="shares must be >= 0"):
            generate_diurnal_load(0, 10, vocab=97, phases=[
                {"name": "a", "share": -1.0}])
        with pytest.raises(ValueError, match="positive rate"):
            generate_diurnal_load(0, 10, vocab=97, phases=[
                {"name": "a", "rate": 0.0}])
        with pytest.raises(ValueError, match="tenant shares"):
            generate_diurnal_load(0, 10, vocab=97,
                                  tenants=[{"id": "a", "share": 0.0}])


# --------------------------- satellite: mid-flight fleet membership

class TestFleetMembership:
    def test_warming_replica_is_never_routed(self, model):
        clock = VClock()
        router = FleetRouter([make_engine(model, clock)])
        idx = router.add_replica(make_engine(model, clock))
        assert router.membership == ["serving", "warming"]
        assert router.serving_indices() == [0]
        hs = [router.submit(list(range(2, 10)), 2) for _ in range(6)]
        assert all(p["replica"] == 0 for p in router.placements)
        router.mark_serving(idx)
        assert router.serving_indices() == [0, 1]
        router.submit(list(range(2, 10)), 2)
        drain(router, clock)
        assert all(h.status == "completed" for h in hs)

    def test_reclaiming_replica_drains_and_never_drops(self, model):
        clock = VClock()
        router = FleetRouter([make_engine(model, clock),
                              make_engine(model, clock)])
        # land one request on each replica, then reclaim replica 1
        # while its request is still in flight
        h0 = router.submit(list(range(2, 10)), 4)
        h1 = router.submit(list(range(12, 20)), 4)
        inflight = {p["replica"] for p in router.placements}
        assert inflight == {0, 1}
        router.begin_reclaim(1)
        assert router.membership[1] == "reclaiming"
        # retiring mid-drain must refuse — that is the never-drop
        # guarantee, structurally
        with pytest.raises(RuntimeError, match="draining"):
            router.retire_replica(1)
        # new work only lands on the serving replica
        before = len(router.placements)
        hs = [router.submit(list(range(3, 9)), 2) for _ in range(4)]
        assert all(p["replica"] == 0
                   for p in router.placements[before:])
        drain(router, clock)
        assert h0.status == h1.status == "completed"
        assert all(h.status == "completed" for h in hs)
        router.retire_replica(1)  # drained now: retire succeeds
        assert router.membership[1] == "retired"

    def test_no_serving_replica_raises(self, model):
        clock = VClock()
        router = FleetRouter([make_engine(model, clock)])
        router.begin_reclaim(0)
        with pytest.raises(RuntimeError, match="no serving replica"):
            router.submit(list(range(2, 10)), 2)

    def test_membership_transitions_guarded(self, model):
        clock = VClock()
        router = FleetRouter([make_engine(model, clock),
                              make_engine(model, clock)])
        router.begin_reclaim(1)
        with pytest.raises(ValueError):
            router.mark_serving(1)  # reclaiming cannot re-serve
        router.retire_replica(1)
        with pytest.raises(ValueError):
            router.begin_reclaim(1)  # retired is terminal
        with pytest.raises(ValueError):
            router.retire_replica(0)  # serving cannot retire directly

    def test_stats_expose_membership(self, model):
        clock = VClock()
        router = FleetRouter([make_engine(model, clock)])
        router.add_replica(make_engine(model, clock))
        st = router.stats()
        assert st["membership"] == {"serving": 1, "warming": 1}
        assert [r["membership"] for r in st["replicas"]] == \
            ["serving", "warming"]

    def test_disagg_decode_reclaim_finishes_streams(self, model):
        clock = VClock()
        engines = [make_engine(model, clock, role="prefill",
                               num_slots=4),
                   make_engine(model, clock, role="decode"),
                   make_engine(model, clock, role="decode")]
        router = DisaggRouter(engines)
        hs = [router.submit(list(range(2 + i, 10 + i)), 4)
              for i in range(4)]
        for _ in range(2):
            router.step()
            clock.t += 0.001
        # reclaim one decode worker mid-flight: it takes no NEW
        # migrations but finishes the streams it carries
        router.begin_reclaim(2)
        before = len(router.migrations)
        hs += [router.submit(list(range(20 + i, 28 + i)), 4)
               for i in range(4)]
        drain(router, clock)
        assert all(h.status == "completed" for h in hs)
        assert all(m["dst"] != 2 for m in router.migrations[before:])
        assert len(router.migrations) > before
        router.retire_replica(2)
        assert router.membership == ["serving", "serving", "retired"]

    def test_disagg_add_replica_extends_role_pool(self, model):
        clock = VClock()
        engines = [make_engine(model, clock, role="prefill"),
                   make_engine(model, clock, role="decode")]
        router = DisaggRouter(engines)
        idx = router.add_replica(make_engine(model, clock,
                                             role="decode"))
        assert idx == 2 and router.membership[idx] == "warming"
        assert idx in router._decode_idx
        router.mark_serving(idx)
        hs = [router.submit(list(range(2 + i, 12 + i)), 4)
              for i in range(4)]
        drain(router, clock)
        assert all(h.status == "completed" for h in hs)


# ----------------------------------------- the gang lend/rejoin seam

def make_trainer():
    set_random_seed(0)
    mlp = MLP((8, 16, 3))

    def loss_fn(m, batch, key):
        logits = m(batch["x"])
        return (softmax_cross_entropy_sparse(logits,
                                             batch["y"]).mean(), {})

    return Trainer(mlp, SGDOptimizer(0.1), loss_fn, donate=False)


def make_gang(tmpdir, world=4, seed=0):
    def data_fn(s):
        rng = np.random.default_rng(seed * 100003 + s)
        x = rng.standard_normal((16, 8)).astype(np.float32)
        return {"x": x, "y": (x[:, 0] > 0).astype(np.int32)}

    return ElasticGang(make_trainer(), str(tmpdir), world_size=world,
                       data_fn=data_fn, global_batch_size=16,
                       seed=seed, save_every=2)


class TestGangLend:
    def test_lend_shrinks_with_zero_replay(self, tmp_path):
        jr = obs_journal.EventJournal(clock=lambda: 0.0)
        with obs_journal.use(jr):
            g = make_gang(tmp_path / "g")
            g.run_until(3)
            lent = g.lend(1)
            assert lent == [3] and g.live_world == 3
            g.run_until(4)  # the next step rescales, then steps
        assert g.world_size == 3 and g.live_world == 3
        lost = [e for e in jr.of_kind("worker_lost") if e["rank"] == 3]
        assert lost and lost[-1]["reason"] == "leased"
        rescale = jr.of_kind("gang_rescale")[-1]
        # save-at-lend: the restore resumes at the lend step — nothing
        # is replayed
        assert rescale["resumed_step"] == 3
        assert rescale["new_world"] == 3

    def test_lend_guards(self, tmp_path):
        g = make_gang(tmp_path / "g", world=2)
        g.run_until(1)
        with pytest.raises(ValueError, match="n >= 1"):
            g.lend(0)
        with pytest.raises(GangError, match="keep at least one"):
            g.lend(2)

    def test_lend_rejoin_losses_bitwise_vs_uninterrupted(self, tmp_path):
        base = make_gang(tmp_path / "base", world=4)
        base.run_until(12)

        g = make_gang(tmp_path / "elastic", world=4)
        g.run_until(3)
        g.lend(1)
        g.run_until(7)  # runs at world 3
        assert g.world_size == 3
        g.rejoin(1)
        g.run_until(12)  # back at world 4
        assert g.world_size == 4
        assert g.losses_by_step == base.losses_by_step


# --------------------------------------------- broker unit loop (fakes)

class _FakeSLO:
    multi_tenant = False

    def __init__(self):
        self.pressure = 0.0

    def shed_pressure(self) -> float:
        return self.pressure


class _FakeBatcher:
    def __init__(self):
        self.idle = True


class _FakeEngine:
    def __init__(self):
        self.slo = _FakeSLO()
        self.batcher = _FakeBatcher()


class _FakeFleet:
    def __init__(self, n=1):
        self.engines = [_FakeEngine() for _ in range(n)]
        self._membership = ["serving"] * n
        self.calls = []

    def serving_indices(self):
        return [i for i, s in enumerate(self._membership)
                if s == "serving"]

    def add_replica(self, engine, *, warming=True):
        self.engines.append(engine)
        self._membership.append("warming" if warming else "serving")
        self.calls.append(("add", len(self.engines) - 1))
        return len(self.engines) - 1

    def mark_serving(self, i):
        self._membership[i] = "serving"
        self.calls.append(("serve", i))

    def begin_reclaim(self, i):
        self._membership[i] = "reclaiming"
        self.calls.append(("reclaim", i))

    def retire_replica(self, i):
        self._membership[i] = "retired"
        self.calls.append(("retire", i))


class _FakeGang:
    def __init__(self, world=4):
        self.world_size = world
        self._dead: set = set()
        self.generation = 0
        self.lend_calls = []
        self.rejoined = 0

    @property
    def live_world(self):
        return self.world_size - len(self._dead)

    def lend(self, n=1):
        live = [w for w in range(self.world_size)
                if w not in self._dead]
        out = live[-n:]
        for w in out:
            self._dead.add(w)
        self.lend_calls.append(out)
        return out

    def rejoin(self, n=1):
        self.world_size = self.live_world + n
        self._dead = set()
        self.generation += 1
        self.rejoined += n


def mk_broker(fleet, gang, **cfg_kw):
    cfg_kw.setdefault("sustain_ticks", 2)
    cfg_kw.setdefault("cooldown_ticks", 3)
    cfg_kw.setdefault("min_train_world", 1)
    b = CapacityBroker(BrokerConfig(**cfg_kw), gang=gang, fleet=fleet,
                       replica_factory=lambda lease, plan:
                       _FakeEngine(),
                       registry=obs.MetricsRegistry())
    return b


class TestBrokerLoop:
    def test_grant_needs_sustain_then_cooldown_binds(self):
        fleet, gang = _FakeFleet(), _FakeGang()
        b = mk_broker(fleet, gang)
        fleet.engines[0].slo.pressure = 1.0
        assert b.tick() is None          # streak 1 < sustain 2
        assert b.tick() == "lease_grant"
        assert gang.lend_calls == [[3]]
        assert fleet._membership == ["serving", "warming"]
        assert b.tick() is None          # cooldown
        assert b.tick() is None
        assert b.tick() == "lease_grant"  # cooldown over, streak held
        assert gang.lend_calls == [[3], [2]]

    def test_hysteresis_band_sustains_nothing(self):
        fleet, gang = _FakeFleet(), _FakeGang()
        b = mk_broker(fleet, gang)
        fleet.engines[0].slo.pressure = 1.0
        b.tick()
        fleet.engines[0].slo.pressure = 0.5  # inside the band
        for _ in range(10):
            assert b.tick() is None
        assert gang.lend_calls == []

    def test_grant_denied_at_floor(self):
        fleet, gang = _FakeFleet(), _FakeGang(world=2)
        b = mk_broker(fleet, gang, min_train_world=2)
        fleet.engines[0].slo.pressure = 1.0
        b.tick()
        assert b.tick() == "grant_denied"
        assert gang.lend_calls == [] and len(fleet.engines) == 1
        assert b.actions[-1]["action"] == "grant_denied"

    def test_lifo_reclaim_with_drain(self):
        fleet, gang = _FakeFleet(), _FakeGang(world=5)
        b = mk_broker(fleet, gang, cooldown_ticks=0)
        fleet.engines[0].slo.pressure = 1.0
        b.tick(); b.tick()               # grant lease 0 (chip 4)
        b.tick()                         # lease 0 warms -> serving
        b.tick()                         # grant lease 1 (chip 3)
        assert [lease.chip for lease in b.leases] == [4, 3]
        fleet.engines[0].slo.pressure = 0.0
        b.tick()
        assert b.tick() == "lease_reclaim"
        # LIFO: the newest lease (chip 3) goes home first
        assert b.leases[1].state == "reclaiming"
        assert b.leases[0].state in ("warming", "serving")
        # replica 2 (lease 1) still draining: no return yet
        fleet.engines[2].batcher.idle = False
        b.tick()
        assert b.leases[1].state == "reclaiming" and gang.rejoined == 0
        fleet.engines[2].batcher.idle = True
        b.tick()
        assert b.leases[1].state == "returned"
        assert gang.rejoined == 1
        assert ("retire", 2) in fleet.calls

    def test_warm_gate_blocks_serving(self):
        fleet, gang = _FakeFleet(), _FakeGang()
        ready = {"warm": False}
        b = CapacityBroker(
            BrokerConfig(sustain_ticks=1, cooldown_ticks=0),
            gang=gang, fleet=fleet,
            replica_factory=lambda lease, plan:
            (_FakeEngine(), lambda: ready["warm"]),
            registry=obs.MetricsRegistry())
        fleet.engines[0].slo.pressure = 1.0
        b.tick()
        assert b.leases[0].state == "warming"
        for _ in range(3):
            b.tick()
            assert b.leases[0].state == "warming"
            assert fleet._membership[1] == "warming"
        ready["warm"] = True
        b.tick()
        assert b.leases[0].state == "serving"
        assert fleet._membership[1] == "serving"

    def test_dry_run_decides_identically_actuates_nothing(self):
        jr_live = obs_journal.EventJournal(clock=lambda: 0.0)
        jr_dry = obs_journal.EventJournal(clock=lambda: 0.0)
        script = [1.0] * 6 + [0.0] * 8

        def run(dry, jr):
            fleet, gang = _FakeFleet(), _FakeGang()
            b = mk_broker(fleet, gang, dry_run=dry, cooldown_ticks=2)
            out = []
            with obs_journal.use(jr):
                for p in script:
                    fleet.engines[0].slo.pressure = p
                    out.append(b.tick())
            return b, fleet, gang, out

        b_live, _fl, _gl, acts_live = run(False, jr_live)
        b_dry, fleet_dry, gang_dry, acts_dry = run(True, jr_dry)
        assert acts_live == acts_dry
        # identical decision stream: same kinds, chips, lease ids
        strip = lambda e: {k: v for k, v in sorted(e.items())
                           if k not in ("seq", "ts", "dry_run")}
        assert [strip(e) for e in jr_live.events
                if e["kind"] in ("lease_grant", "lease_reclaim")] == \
            [strip(e) for e in jr_dry.events
             if e["kind"] in ("lease_grant", "lease_reclaim")]
        assert all(e["dry_run"] for e in jr_dry.events
                   if e["kind"].startswith("lease"))
        # ... while actuating nothing
        assert gang_dry.lend_calls == [] and gang_dry.rejoined == 0
        assert fleet_dry.calls == []
        assert gang_dry.live_world == 4
        # the shadow books still bind the floor
        assert b_dry.train_world() == b_live.train_world()

    def test_metrics_count_actuations_only(self):
        reg = obs.MetricsRegistry()
        fams = broker_families(reg)
        fleet, gang = _FakeFleet(), _FakeGang()
        b = CapacityBroker(
            BrokerConfig(sustain_ticks=1, cooldown_ticks=0),
            gang=gang, fleet=fleet,
            replica_factory=lambda lease, plan: _FakeEngine(),
            registry=reg)
        fleet.engines[0].slo.pressure = 1.0
        b.tick()
        assert fams["leases"].labels(direction="grant").value == 1
        assert fams["chips_lent"].labels().value == 1
        fleet.engines[0].slo.pressure = 0.0
        b.tick()  # serving
        b.tick()  # reclaim decision
        b.tick()  # drained -> returned
        assert fams["leases"].labels(direction="reclaim").value == 1
        assert fams["chips_lent"].labels().value == 0

    def test_config_validation_and_env(self, monkeypatch):
        with pytest.raises(ValueError, match="hysteresis"):
            BrokerConfig(grant_on=0.2, grant_off=0.5)
        with pytest.raises(ValueError, match="sustain"):
            BrokerConfig(sustain_ticks=0)
        with pytest.raises(ValueError, match="chips_per_grant"):
            BrokerConfig(chips_per_grant=0)
        with pytest.raises(ValueError, match="min_train_world"):
            BrokerConfig(min_train_world=0)
        monkeypatch.setenv("HETU_TPU_BROKER_GRANT_ON", "0.8")
        monkeypatch.setenv("HETU_TPU_BROKER_DRY_RUN", "true")
        monkeypatch.setenv("HETU_TPU_BROKER_SUSTAIN_TICKS", "5")
        cfg = BrokerConfig.from_env(cooldown_ticks=2)
        assert cfg.grant_on == 0.8 and cfg.dry_run
        assert cfg.sustain_ticks == 5 and cfg.cooldown_ticks == 2

    def test_summary_and_endpoint(self):
        fleet, gang = _FakeFleet(), _FakeGang()
        b = mk_broker(fleet, gang, sustain_ticks=1, cooldown_ticks=0)
        fleet.engines[0].slo.pressure = 1.0
        b.tick()
        s = b.summary()
        assert s["chips_lent"] == 1 and s["tick"] == 1
        assert s["leases"][0]["state"] == "warming"
        assert s["leases_by_state"] == {"warming": 1}
        assert s["recent_actions"][-1]["action"] == "lease_grant"
        with broker_use(b):
            assert get_broker() is b
            with obs.serve() as srv:
                with urllib.request.urlopen(srv.url + "/broker",
                                            timeout=10) as r:
                    body = json.loads(r.read())
        assert body["chips_lent"] == 1
        assert body["leases"][0]["chip"] == 3
        assert get_broker() is not b
        with obs.serve() as srv:
            with urllib.request.urlopen(srv.url + "/broker",
                                        timeout=10) as r:
                assert json.loads(r.read()) == {"installed": False}

    def test_fleet_broker_endpoint(self, tmp_path):
        from hetu_tpu.obs.fleet import SnapshotPublisher, serve_fleet
        jr = obs_journal.EventJournal(clock=lambda: 0.0)
        with obs_journal.use(jr):
            fams = broker_families(obs.get_registry())
            fams["leases"].labels(direction="grant").inc()
            fams["chips_lent"].labels().set(1.0)
            obs_journal.record("lease_grant", lease_id=0, chip=3,
                               from_role="train", to_role="serve",
                               trigger="slo_burn", plan_sha="x" * 64,
                               generation=0, dry_run=False)
            SnapshotPublisher(str(tmp_path), 0,
                              clock=lambda: 0.0).publish()
        srv = serve_fleet(str(tmp_path), port=0)
        try:
            with urllib.request.urlopen(srv.url + "/fleet/broker",
                                        timeout=10) as r:
                body = json.loads(r.read())
        finally:
            srv.stop()
        assert body["workers"] == 1
        assert body["leases"].get("grant", 0) >= 1
        assert body["chips_lent"] >= 1.0
        tail = body["leases_journal"]
        assert tail and tail[-1]["kind"] == "lease_grant"
        assert tail[-1]["publisher"] == 0 and tail[-1]["chip"] == 3


# ------------------------------------------- seeded diurnal acceptance

@pytest.fixture(scope="module")
def episodes(tmp_path_factory):
    """Each scenario once, shared across the acceptance assertions."""
    root = tmp_path_factory.mktemp("broker_episodes")

    def run(tag, **kw):
        return run_broker_episode(str(root / tag), seed=0, **kw)

    return {
        "brokered": run("brokered", brokered=True),
        "replay": run("replay", brokered=True),
        "split_a": run("split_a", brokered=False, train_world=4,
                       serve_replicas=1),
        "split_b": run("split_b", brokered=False, train_world=3,
                       serve_replicas=2),
        "dry": run("dry", brokered=True, dry_run=True),
        "dry2": run("dry2", brokered=True, dry_run=True),
    }


class TestBrokerAcceptance:
    def test_full_lease_lifecycle(self, episodes):
        r = episodes["brokered"]
        grants = [e for e in r["lease_events"]
                  if e["kind"] == "lease_grant"]
        reclaims = [e for e in r["lease_events"]
                    if e["kind"] == "lease_reclaim"]
        assert grants and reclaims
        assert all(e["trigger"] == "slo_burn" for e in grants)
        assert all(e["trigger"] == "pressure_release"
                   for e in reclaims)
        # every grant carries the signed replan it rode on
        assert all(len(e["plan_sha"]) == 64 for e in grants)
        # LIFO: reclaims walk the grant order backwards
        assert [e["lease_id"] for e in reclaims] == \
            sorted((e["lease_id"] for e in grants), reverse=True)
        # every lease came home: the day ends with the gang whole
        assert all(lease["state"] == "returned" for lease in r["leases"])
        assert r["chips_lent"] == 0
        assert r["final_world"] == 4
        assert r["membership"][0] == "serving"
        assert set(r["membership"][1:]) <= {"retired"}

    def test_brokered_jointly_beats_both_static_splits(self, episodes):
        br = episodes["brokered"]
        a, b = episodes["split_a"], episodes["split_b"]
        # the broker out-trains the serve-heavy split AND out-serves
        # the train-heavy split...
        assert br.goodput > b.goodput
        assert br.violations < a.violations
        # ...and NEITHER static split weakly dominates it on the joint
        # (violations, goodput) objective
        for split in (a, b):
            assert not (split.violations <= br.violations
                        and split.goodput >= br.goodput), \
                f"static split dominates: {split.violations}/" \
                f"{split.goodput} vs {br.violations}/{br.goodput}"

    def test_loss_trajectory_matches_uninterrupted_run(self, episodes):
        br, a = episodes["brokered"], episodes["split_a"]
        # split A is the SAME episode with the broker disabled: same
        # seed, same construction order, world 4 throughout — its loss
        # curve IS the uninterrupted run.  At equal total steps the
        # brokered gang (lend -> world 3 -> rejoin -> world 4) must
        # match it bitwise: save-at-lend replays nothing and partition
        # invariance absorbs the world changes.
        assert br["train_steps"] > 0
        assert set(br["losses_by_step"]) <= set(a["losses_by_step"])
        mismatch = [s for s, v in br["losses_by_step"].items()
                    if a["losses_by_step"][s] != v]
        assert mismatch == []

    def test_same_seed_replay_is_bitwise(self, episodes):
        r1, r2 = episodes["brokered"], episodes["replay"]
        assert r1["lease_events"] == r2["lease_events"]
        assert r1["decisions"] == r2["decisions"]
        assert r1["plan_shas"] == r2["plan_shas"]
        assert r1["placements"] == r2["placements"]
        assert r1["streams"] == r2["streams"]
        assert r1["losses_by_step"] == r2["losses_by_step"]
        assert r1["leases"] == r2["leases"]
        assert r1["world_by_tick"] == r2["world_by_tick"]

    def test_dry_run_decides_and_actuates_nothing(self, episodes):
        dry, live = episodes["dry"], episodes["brokered"]
        # dry-vs-dry is itself bitwise
        assert dry["lease_events"] == episodes["dry2"]["lease_events"]
        assert dry["decisions"] == episodes["dry2"]["decisions"]
        assert dry["losses_by_step"] == \
            episodes["dry2"]["losses_by_step"]
        # the first grant decision matches the live broker exactly:
        # same tick (virtual ts), same chip, same signed plan
        g_live = [e for e in live["lease_events"]
                  if e["kind"] == "lease_grant"][0]
        g_dry = [e for e in dry["lease_events"]
                 if e["kind"] == "lease_grant"][0]
        strip = lambda e: {k: v for k, v in sorted(e.items())
                           if k != "dry_run"}
        assert strip(g_live) == strip(g_dry)
        assert g_dry["dry_run"] and not g_live["dry_run"]
        # ... while actuating nothing: no replicas added, no chips
        # lent, the gang trains the full uninterrupted schedule
        assert dry["membership"] == ["serving"]
        assert dry["chips_lent"] == 0
        assert dry["final_world"] == 4
        assert dry["train_steps"] == \
            episodes["split_a"]["train_steps"]

    def test_world_follows_the_leases(self, episodes):
        r = episodes["brokered"]
        worlds = r["world_by_tick"]
        # the gang visibly shrinks while the lease is out and ends the
        # night back at full width
        assert min(worlds) == 3 and worlds[0] == 4 and worlds[-1] == 4
