"""T5/ViT/Swin model families (Galvatron parity — SURVEY §2.5): forward
shapes, loss finiteness, gradient flow, jit-compilability, and sharding-
strategy compatibility on the virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_tpu.core import set_random_seed

pytestmark = pytest.mark.slow  # Galvatron model zoo (ViT/Swin/T5) — jit-heavy
from hetu_tpu.models import (
    Swin,
    SwinConfig,
    T5Config,
    T5ForConditionalGeneration,
    ViT,
    ViTConfig,
)


def _t5_tiny():
    return T5Config(vocab_size=256, d_model=32, d_kv=8, d_ff=64,
                    num_layers=2, num_heads=4)


def _vit_tiny():
    return ViTConfig(image_size=32, patch_size=8, hidden_size=32,
                     num_layers=2, num_heads=4, num_classes=10)


def _swin_tiny():
    return SwinConfig(image_size=32, patch_size=2, embed_dim=16,
                      depths=(2, 2), num_heads=(2, 4), window_size=4,
                      num_classes=10)


def test_t5_forward_and_loss():
    set_random_seed(0)
    cfg = _t5_tiny()
    model = T5ForConditionalGeneration(cfg)
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    logits = jax.jit(lambda m, a, b: m(a, b))(model, src, tgt)
    assert logits.shape == (2, 8, cfg.vocab_size)
    loss, aux = model.loss(src, tgt, tgt)
    assert np.isfinite(float(loss))


def test_t5_decoder_is_causal():
    """Future target tokens must not change earlier logits."""
    set_random_seed(1)
    cfg = _t5_tiny()
    model = T5ForConditionalGeneration(cfg)
    rng = np.random.default_rng(1)
    src = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), jnp.int32)
    out1 = model(src, tgt)
    tgt2 = tgt.at[0, -1].set((tgt[0, -1] + 7) % cfg.vocab_size)
    out2 = model(src, tgt2)
    np.testing.assert_allclose(np.asarray(out1[0, :-1]),
                               np.asarray(out2[0, :-1]), atol=1e-5)


def test_t5_relative_bias_buckets():
    from hetu_tpu.models.t5 import relative_position_bucket
    pos = jnp.arange(-10, 11)
    b = relative_position_bucket(pos, bidirectional=True, num_buckets=32,
                                 max_distance=128)
    assert int(b.min()) >= 0 and int(b.max()) < 32
    # symmetric offsets land in distinct halves
    assert int(b[0]) != int(b[-1])


def test_t5_grads_flow():
    set_random_seed(2)
    cfg = _t5_tiny()
    model = T5ForConditionalGeneration(cfg)
    rng = np.random.default_rng(2)
    src = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    g = jax.grad(lambda m: m.loss(src, tgt, tgt)[0])(model)
    assert float(jnp.abs(g.t5.shared.weight).sum()) > 0
    assert float(jnp.abs(g.t5.decoder.blocks[0].cross.wq).sum()) > 0
    assert float(jnp.abs(g.t5.encoder.rel_bias.table).sum()) > 0


def test_vit_forward_and_grads():
    set_random_seed(3)
    cfg = _vit_tiny()
    model = ViT(cfg)
    imgs = jnp.asarray(np.random.default_rng(3).normal(size=(2, 32, 32, 3)),
                       jnp.float32)
    logits = jax.jit(lambda m, x: m(x))(model, imgs)
    assert logits.shape == (2, 10)
    labels = jnp.asarray([1, 2], jnp.int32)
    g = jax.grad(lambda m: m.loss(imgs, labels)[0])(model)
    assert float(jnp.abs(g.patch_embed.proj.w).sum()) > 0
    assert float(jnp.abs(g.cls_token).sum()) > 0


def test_swin_forward_and_grads():
    set_random_seed(4)
    cfg = _swin_tiny()
    model = Swin(cfg)
    imgs = jnp.asarray(np.random.default_rng(4).normal(size=(2, 32, 32, 3)),
                       jnp.float32)
    logits = jax.jit(lambda m, x: m(x))(model, imgs)
    assert logits.shape == (2, 10)
    labels = jnp.asarray([3, 4], jnp.int32)
    g = jax.grad(lambda m: m.loss(imgs, labels)[0])(model)
    assert float(jnp.abs(g.stages[0][0].attn.bias_table).sum()) > 0
    assert float(jnp.abs(g.merges[0].proj.w).sum()) > 0


def test_swin_rejects_untileable_config():
    import dataclasses as dc
    import pytest
    set_random_seed(4)
    cfg = dc.replace(_swin_tiny(), window_size=6)  # 16 % 6 != 0
    with pytest.raises(ValueError, match="divisible"):
        Swin(cfg)
    cfg = dc.replace(_swin_tiny(), patch_size=5)  # 32 % 5 != 0
    with pytest.raises(ValueError, match="divisible"):
        Swin(cfg)


def test_swin_shifted_window_mask_blocks_cross_region():
    from hetu_tpu.models.swin import _shift_mask
    m = _shift_mask(8, 8, 4, 2)
    assert m.shape == (4, 16, 16)
    assert (m <= 0).all() and (m < 0).any()


def test_vit_trains_under_strategy():
    from hetu_tpu.exec import Trainer
    from hetu_tpu.optim import AdamOptimizer
    from hetu_tpu.parallel.mesh import MeshSpec, make_mesh
    from hetu_tpu.parallel.spec import DP_RULES
    from hetu_tpu.parallel.strategies import ShardingStrategy

    set_random_seed(5)
    mesh = make_mesh(MeshSpec(dp=8))
    model = ViT(_vit_tiny())
    strategy = ShardingStrategy(mesh=mesh, rules=DP_RULES, batch_axes="dp")
    tr = Trainer(model, AdamOptimizer(1e-3),
                 lambda m, b, k: m.loss(b["x"], b["y"], key=k),
                 strategy=strategy)
    rng = np.random.default_rng(5)
    batch = {
        "x": jnp.asarray(rng.normal(size=(16, 32, 32, 3)), jnp.float32),
        "y": jnp.asarray(rng.integers(0, 10, (16,)), jnp.int32),
    }
    losses = [float(tr.step(batch)["loss"]) for _ in range(10)]
    assert losses[-1] < losses[0]
