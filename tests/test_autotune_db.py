"""The shared persistent autotune database (ops/pallas/autotune.py):
cross-kernel entries, cross-process round-trip, concurrent writers
merging without loss (the locked atomic save), legacy cache migration +
env-var deprecation, heuristic override in each consumer kernel, and the
``hetu_tune_*`` observability family.
"""

import json
import multiprocessing

import pytest

from hetu_tpu import obs
from hetu_tpu.ops.pallas import autotune as at

pytestmark = pytest.mark.pallas


@pytest.fixture
def tune_db(tmp_path, monkeypatch):
    path = tmp_path / "tune_db.json"
    monkeypatch.setenv(at._CACHE_ENV, str(path))
    monkeypatch.delenv(at._LEGACY_CACHE_ENV, raising=False)
    at.clear_tune_cache()
    yield path
    at.clear_tune_cache()


def test_record_and_lookup_multi_kernel(tune_db):
    at.record_entry("lm_head", "N64|E32|V256", {"block_n": 32, "block_v": 128})
    at.record_entry("paged_decode", "h4|d64|p16", {"head_block": 2})
    at.record_entry("fused_ln", "T128|D256|s6", {"block_rows": 64})
    # all three kernels' entries live in ONE file, namespaced by kernel
    disk = json.loads(tune_db.read_text())
    assert {k.split("|")[0] for k in disk} == {"lm_head", "paged_decode",
                                              "fused_ln"}
    # a fresh process (memo cleared) sees them
    at.clear_tune_cache()
    assert at.tuned_entry("lm_head", "N64|E32|V256")["block_n"] == 32
    assert at.tuned_entry("paged_decode", "h4|d64|p16")["head_block"] == 2
    assert at.tuned_entry("fused_ln", "T128|D256|s6")["block_rows"] == 64
    assert at.tuned_entry("flash", "8x8|d4|c0") is None


def _writer(path, kernel, n, out_q):
    """Subprocess body: hammer n entries into the shared DB."""
    import os
    os.environ[at._CACHE_ENV] = path
    at.clear_tune_cache()
    for i in range(n):
        at.record_entry(kernel, f"sig{i}", {"i": i, "by": kernel})
    out_q.put("done")


def test_concurrent_writers_merge_without_loss(tune_db):
    """Acceptance: two processes recording entries concurrently into the
    same DB file — every entry from BOTH survives (exclusive-lock merge
    through the atomic writer; the old bare read-modify-write lost the
    race loser's whole batch)."""
    n = 25
    # spawn, not fork: the parent has initialized (multithreaded) jax
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    ps = [ctx.Process(target=_writer, args=(str(tune_db), kern, n, q))
          for kern in ("lm_head", "paged_decode")]
    for p in ps:
        p.start()
    for p in ps:
        assert q.get(timeout=60) == "done"
    for p in ps:
        p.join(30)
        assert p.exitcode == 0
    disk = json.loads(tune_db.read_text())
    for kern in ("lm_head", "paged_decode"):
        for i in range(n):
            key = f"{kern}|{at._device_kind()}|sig{i}"
            assert disk[key] == {"i": i, "by": kern}, key
    # the DB is valid JSON (no torn write) and the lock file is benign
    assert len(disk) == 2 * n


def test_legacy_env_var_honored_with_deprecation(tmp_path, monkeypatch):
    """Satellite: HETU_TPU_FLASH_TUNE_CACHE still works (DeprecationWarning)
    and the new name wins when both are set."""
    old = tmp_path / "old_flash.json"
    new = tmp_path / "new_db.json"
    monkeypatch.delenv(at._CACHE_ENV, raising=False)
    monkeypatch.setenv(at._LEGACY_CACHE_ENV, str(old))
    at.clear_tune_cache()
    with pytest.warns(DeprecationWarning, match=at._CACHE_ENV):
        at.record_entry("lm_head", "N8|E8|V128", {"block_n": 8,
                                                  "block_v": 128})
    assert old.exists() and not new.exists()
    monkeypatch.setenv(at._CACHE_ENV, str(new))
    at.clear_tune_cache()
    at.record_entry("lm_head", "N8|E8|V128", {"block_n": 16, "block_v": 128})
    assert new.exists()
    at.clear_tune_cache()


def test_legacy_flash_keys_migrate_on_load(tune_db):
    """A pre-unification cache file (bare ``{kind}|{sig}`` flash keys) is
    readable: keys migrate into the flash| namespace on load and the
    flash lookup (incl. the complement fallback) still answers."""
    kind = at._device_kind()
    tune_db.write_text(json.dumps({
        f"{kind}|128x128|d64|c1": {"block_q": 128, "block_k": 128}}))
    at.clear_tune_cache()
    assert at.tuned_blocks(128, 128, 64, causal=True) == (128, 128)
    assert at.tuned_blocks(128, 128, 64, causal=False) == (128, 128)
    # a save republishes under the migrated key, preserving the entry
    at.record_entry("lm_head", "N8|E8|V128", {"block_n": 8, "block_v": 128})
    disk = json.loads(tune_db.read_text())
    assert f"flash|{kind}|128x128|d64|c1" in disk
    assert f"{kind}|128x128|d64|c1" not in disk


def test_consumers_pick_up_entries(tune_db):
    """Each kernel's block-selection helper prefers the DB: fused_ln row
    blocks, lm_head (via its None-default path), paged_decode head_block
    (exercised end to end: a tuned head_block of 1 still runs and matches
    — see test_paged_decode for the numeric invariance)."""
    from hetu_tpu.ops.pallas.fused_ln import _pick_block
    heur = _pick_block(128, 256, 6)
    tuned = 32 if heur != 32 else 16
    at.record_entry("fused_ln", "T128|D256|s6", {"block_rows": tuned})
    assert _pick_block(128, 256, 6) == tuned
    # an entry that no longer divides T falls back to the heuristic
    at.record_entry("fused_ln", "T120|D256|s6", {"block_rows": 32})
    assert _pick_block(120, 256, 6) != 32

    from hetu_tpu.ops.pallas.paged_decode import _head_block
    at.record_entry("paged_decode", "h4|d8|p4", {"head_block": 2})
    assert _head_block(4, 8, 4, None) == 2
    assert _head_block(4, 8, 4, 4) == 4  # explicit arg outranks the DB
    at.record_entry("paged_decode", "h6|d8|p4", {"head_block": 4})
    assert _head_block(6, 8, 4, None) == 6  # non-divisor entry ignored


def test_tune_metrics_exposed(tune_db):
    """hits/misses/retunes ride the hetu_tune_* counter family and appear
    in the Prometheus exposition."""
    reg = obs.get_registry()
    s0 = reg.snapshot()
    at.tuned_entry("lm_head", "Nx|missing")               # miss
    at.record_entry("lm_head", "Nx|missing", {"block_n": 8, "block_v": 128})
    at.tuned_entry("lm_head", "Nx|missing")               # hit
    at.record_entry("lm_head", "Nx|missing", {"block_n": 16,
                                              "block_v": 128})  # retune
    d = reg.delta(reg.snapshot(), s0)
    assert d['hetu_tune_misses_total{kernel="lm_head"}'] == 1
    assert d['hetu_tune_hits_total{kernel="lm_head"}'] == 1
    assert d['hetu_tune_retunes_total{kernel="lm_head"}'] == 1
    text = reg.render_prometheus()
    for name in ("hetu_tune_hits_total", "hetu_tune_misses_total",
                 "hetu_tune_retunes_total"):
        assert name in text
