"""Pipeline parallelism tests on the virtual 8-device CPU mesh.

Oracle: a pipelined stack must be numerically identical to running the same
blocks sequentially on one device — the cross-parallelism equivalence
discipline of the reference's validate_results.py
(reference: examples/runner/parallel/validate_results.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from hetu_tpu.core import set_random_seed
from hetu_tpu.core.module import Module
from hetu_tpu.core.rng import next_key
from hetu_tpu.init import normal
from hetu_tpu.layers import TransformerBlock
from hetu_tpu.parallel.mesh import MeshSpec, make_mesh
from hetu_tpu.parallel.pipeline import (
    Pipelined, spmd_pipeline, stack_modules, stage_partition,
)


@pytest.fixture
def pp_mesh():
    return make_mesh(MeshSpec(pp=4, dp=2), devices=jax.devices())


class Tiny(Module):
    def __init__(self, d):
        self.w = normal(stddev=0.5)(next_key(), (d, d), jnp.float32)
        self.w_axes = ("in", "out")

    def __call__(self, x, mask=None, *, key=None, training=False):
        return jnp.tanh(x @ self.w) + x


def test_stage_partition():
    assert [list(r) for r in stage_partition(7, 3)] == [[0, 1, 2], [3, 4], [5, 6]]
    assert [len(r) for r in stage_partition(8, 4)] == [2, 2, 2, 2]


def test_spmd_pipeline_matches_sequential(pp_mesh):
    set_random_seed(0)
    d, B, M = 8, 8, 4
    blocks = [Tiny(d) for _ in range(4)]
    x = jnp.asarray(np.random.default_rng(0).normal(size=(B, 16, d)), jnp.float32)

    ref = x
    for b in blocks:
        ref = b(ref)

    params = stack_modules(blocks)

    def stage_fn(blk, h, ex, k):
        return blk(h)

    out = jax.jit(
        lambda p, v: spmd_pipeline(
            stage_fn, p, v, mesh=pp_mesh, n_microbatches=M
        )
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_pipelined_module_fwd_and_grad(pp_mesh):
    set_random_seed(1)
    d, B = 8, 8
    blocks = [Tiny(d) for _ in range(8)]  # 2 layers per stage
    x = jnp.asarray(np.random.default_rng(1).normal(size=(B, 4, d)), jnp.float32)

    pipe = Pipelined(blocks, n_microbatches=4, mesh=pp_mesh, remat=True)
    seq = Pipelined(blocks, n_microbatches=4, mesh=None)  # degenerate scan path

    out_p = jax.jit(lambda m, v: m(v))(pipe, x)
    out_s = jax.jit(lambda m, v: m(v))(seq, x)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_s),
                               rtol=1e-5, atol=1e-5)

    # grads wrt stacked params must match the sequential oracle
    def loss_p(m, v):
        return (m(v) ** 2).mean()

    gp = jax.jit(jax.grad(loss_p))(pipe, x)
    gs = jax.jit(jax.grad(loss_p))(seq, x)
    np.testing.assert_allclose(
        np.asarray(gp.stacked.w), np.asarray(gs.stacked.w), rtol=1e-4, atol=1e-5
    )


# slow tier (r5 budget, 1-core box): dryrun config A runs the Pipelined transformer every driver round
@pytest.mark.slow
def test_pipelined_transformer_blocks(pp_mesh):
    """Real transformer blocks through the pipeline, with mask extras."""
    set_random_seed(2)
    d, H, B, S = 16, 4, 8, 12
    blocks = [TransformerBlock(d, H, causal=True) for _ in range(4)]
    x = jnp.asarray(np.random.default_rng(2).normal(size=(B, S, d)), jnp.float32)

    pipe = Pipelined(blocks, n_microbatches=2, mesh=pp_mesh, remat=False)
    ref = x
    for b in blocks:
        ref = b(ref)

    out = jax.jit(lambda m, v: m(v))(pipe, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)
