"""Fused residual+dropout+LayerNorm kernel vs the composed oracle.

Oracle-comparison style (reference tests compare CUDA kernels vs numpy);
kernels run under the Pallas interpreter on CPU.  The fused kernel's
dropout regenerates ops.dropout's exact bits in-register, so the oracle
is literally ``layer_norm(x + ops.dropout(y, rate, key))``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_tpu.ops.nn import dropout, layer_norm
from hetu_tpu.ops.pallas.fused_ln import fused_residual_dropout_ln


def _case(shape, D, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((*shape, D)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((*shape, D)), jnp.float32)
    scale = jnp.asarray(1.0 + 0.1 * rng.standard_normal(D), jnp.float32)
    bias = jnp.asarray(0.1 * rng.standard_normal(D), jnp.float32)
    return x, y, scale, bias


def _oracle(x, y, scale, bias, rate, key):
    v = x + (dropout(y, rate, key) if rate > 0.0 and key is not None else y)
    return layer_norm(v, scale, bias, eps=1e-5)


@pytest.mark.parametrize("shape,D", [((4, 32), 256), ((16,), 512),
                                     ((2, 3, 8), 128)])
@pytest.mark.parametrize("rate", [0.0, 0.1])
def test_fused_ln_forward_bit_parity(shape, D, rate):
    """Same bits as ops.dropout + ops.layer_norm — the in-kernel hash
    regen must reproduce the mask exactly."""
    x, y, scale, bias = _case(shape, D)
    key = jax.random.key(11)
    out = fused_residual_dropout_ln(x, y, scale, bias, rate=rate, key=key,
                                    interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_oracle(x, y, scale, bias, rate, key)),
        rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("rate", [0.0, 0.1])
def test_fused_ln_grads(rate):
    x, y, scale, bias = _case((4, 16), 256, seed=3)
    key = jax.random.key(4)

    def loss_fused(x, y, scale, bias):
        o = fused_residual_dropout_ln(x, y, scale, bias, rate=rate,
                                      key=key, interpret=True)
        return jnp.sum(o * jnp.cos(o))  # nontrivial cotangent

    def loss_ref(x, y, scale, bias):
        o = _oracle(x, y, scale, bias, rate, key)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, y, scale, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, y, scale, bias)
    for a, b, name in zip(gf, gr, ("dx", "dy", "dscale", "dbias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_fused_ln_bf16_and_ragged_rows():
    """bf16 activations with fp32 stats; a row count that does not divide
    the preferred block (exercises _pick_block's gcd fallback).  bf16 is
    allclose, not bitwise: the fused path keeps the residual sum in fp32
    (the unfused path rounds it to bf16 before the LN)."""
    x, y, scale, bias = _case((7, 13), 128, seed=5)
    x, y = x.astype(jnp.bfloat16), y.astype(jnp.bfloat16)
    key = jax.random.key(6)
    out = fused_residual_dropout_ln(x, y, scale, bias, rate=0.2, key=key,
                                    interpret=True)
    ref = _oracle(x, y, scale, bias, 0.2, key)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)
    assert out.dtype == jnp.bfloat16


@pytest.mark.slow
def test_transformer_block_fused_ln_matches_unfused():
    """A post-LN TransformerBlock with fused_ln=True computes the same
    function as the unfused path — eval mode exactly, train mode with
    dropout ON too (the fused kernel regenerates ops.dropout's bits)."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.layers import TransformerBlock

    set_random_seed(0)
    blk = TransformerBlock(128, 4, post_ln=True, dropout_rate=0.1)
    set_random_seed(0)
    blk_f = TransformerBlock(128, 4, post_ln=True, dropout_rate=0.1,
                             fused_ln=True)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((2, 16, 128)), jnp.float32)

    # eval: deterministic, must agree
    np.testing.assert_allclose(np.asarray(blk_f(x)), np.asarray(blk(x)),
                               rtol=2e-5, atol=2e-5)

    # train with a fixed key: same dropout bits -> same output and grads
    key = jax.random.key(3)
    ref = blk(x, key=key, training=True)
    out = blk_f(x, key=key, training=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    gref = jax.grad(lambda m: (m(x, key=key, training=True) ** 2).sum())(blk)
    gout = jax.grad(lambda m: (m(x, key=key, training=True) ** 2).sum())(blk_f)
    for a, b in zip(jax.tree_util.tree_leaves(gout),
                    jax.tree_util.tree_leaves(gref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_fused_ln_rejects_pre_ln_block():
    from hetu_tpu.layers import TransformerBlock

    with pytest.raises(ValueError, match="post_ln"):
        TransformerBlock(64, 2, fused_ln=True)  # default pre-LN


@pytest.mark.slow
def test_bert_fused_ln_trains():
    """BertForPreTraining(fused_ln=True) trains: loss drops through the
    fused kernel's custom vjp."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.exec import Trainer
    from hetu_tpu.models import BertForPreTraining, bert_base
    from hetu_tpu.optim import AdamWOptimizer

    set_random_seed(0)
    cfg = bert_base(num_layers=2, hidden_size=128, num_heads=2,
                    vocab_size=256, fused_ln=True)
    tr = Trainer(BertForPreTraining(cfg),
                 AdamWOptimizer(1e-3, weight_decay=0.01),
                 lambda m, b, k: (m.loss(b["ids"], b["tt"], None, b["mlm"],
                                         b["nsp"], key=k,
                                         training=True)[0], {}))
    rng = np.random.default_rng(0)
    B, S = 4, 32
    b = {"ids": jnp.asarray(rng.integers(0, 256, (B, S)), jnp.int32),
         "tt": jnp.zeros((B, S), jnp.int32),
         "mlm": jnp.asarray(np.where(rng.random((B, S)) < 0.3,
                                     rng.integers(0, 256, (B, S)), -1),
                            jnp.int32),
         "nsp": jnp.asarray(rng.integers(0, 2, (B,)), jnp.int32)}
    losses = [float(tr.step(b)["loss"]) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.9, losses


def test_fused_ln_rejects_rate_one_and_single_word_key():
    x, y, scale, bias = _case((2, 4), 128, seed=8)
    with pytest.raises(ValueError, match="rate"):
        fused_residual_dropout_ln(x, y, scale, bias, rate=1.0,
                                  key=jax.random.key(0), interpret=True)
    # raw single-word key: folded like ops.dropout's words[1 % 1]
    kw1 = jnp.asarray([7], jnp.uint32)
    out = fused_residual_dropout_ln(x, y, scale, bias, rate=0.2, key=kw1,
                                    interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_oracle(x, y, scale, bias, 0.2, kw1)),
        rtol=2e-5, atol=2e-5)
