"""Online inference subsystem: KV-cache pool, sampling helpers, decode
parity, continuous batcher, route table, the /infer endpoint, and the
sustained-load / chaos acceptance tests.
"""

import json
import re
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_tpu import obs
from hetu_tpu.core import set_random_seed
from hetu_tpu.exec import faults
from hetu_tpu.layers.attention import (decode_attention,
                                       dot_product_attention,
                                       ragged_cache_update)
from hetu_tpu.models.gpt import GPT, GPTConfig
from hetu_tpu.ops.random import greedy_sample, temperature_sample, top_k_sample
from hetu_tpu.serve import (AdmissionQueueFull, ContinuousBatcher,
                            KVCachePool, OutOfPages, Request, ServingEngine,
                            generate_load, serve_engine)
from hetu_tpu.serve.kv_cache import SCRATCH_PAGE, gather_views, scatter_views

pytestmark = pytest.mark.serve


def tiny_gpt(seed=0, **kw):
    set_random_seed(seed)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, **kw)
    return GPT(cfg)


class VirtualClock:
    """Deterministic clock the engine tests drive by hand."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------ KV-cache pool

class TestKVCachePool:
    def make(self, pages=9, page=4):
        return KVCachePool(num_layers=1, num_heads=1, head_dim=2,
                           num_pages=pages, page_size=page, max_seq_len=16)

    def test_alloc_free_deterministic_lowest_first(self):
        pool = self.make()
        a = pool.alloc(10, 5)   # 2 pages
        b = pool.alloc(11, 1)   # 1 page
        assert a.pages == [1, 2] and b.pages == [3]
        assert pool.free_pages == 5
        pool.free(10)
        c = pool.alloc(12, 3)   # re-uses the lowest freed pages
        assert c.pages == [1]
        assert pool.alloc(13, 5).pages == [2, 4]

    def test_out_of_pages_is_side_effect_free(self):
        pool = self.make(pages=4)
        pool.alloc(1, 8)  # 2 of 3 usable pages
        free_before = pool.free_pages
        with pytest.raises(OutOfPages):
            pool.alloc(2, 8)
        assert pool.free_pages == free_before
        assert not pool.can_admit(8) and pool.can_admit(4)

    def test_ensure_grows_page_at_a_time(self):
        pool = self.make()
        pt = pool.alloc(5, 3)
        assert len(pt.pages) == 1
        pool.ensure(5, 4)
        assert len(pt.pages) == 1  # still fits
        pool.ensure(5, 5)
        assert len(pt.pages) == 2
        with pytest.raises(ValueError, match="max_seq_len"):
            pool.ensure(5, 17)

    def test_gather_indices_pads_with_scratch(self):
        pool = self.make()
        pool.alloc(7, 6)  # 2 pages
        idx = np.asarray(pool.gather_indices([7, None]))
        assert idx.shape == (2, 4)
        assert list(idx[0]) == [1, 2, SCRATCH_PAGE, SCRATCH_PAGE]
        assert list(idx[1]) == [SCRATCH_PAGE] * 4

    def test_gather_scatter_roundtrip(self):
        pool = self.make()
        pool.alloc(1, 16)
        idx = pool.gather_indices([1])
        kv, vv = gather_views(pool.k, pool.v, idx)
        assert kv.shape == (1, 1, 16, 1, 2)
        marked = kv.at[0, 0, 5, 0, 0].set(42.0)
        k2, v2 = scatter_views(pool.k, pool.v, idx, marked, vv)
        pool.commit(k2, v2)
        kv2, _ = gather_views(pool.k, pool.v, idx)
        assert float(kv2[0, 0, 5, 0, 0]) == 42.0

    def test_defrag_compacts_and_preserves_rows(self):
        pool = self.make(pages=11)
        for sid in (1, 2, 3):
            pool.alloc(sid, 12)  # 3 pages each; pool now fully booked
        # write a recognizable value into each sequence's view
        for sid in (1, 2, 3):
            idx = pool.gather_indices([sid])
            kv, vv = gather_views(pool.k, pool.v, idx)
            pool.commit(*scatter_views(pool.k, pool.v, idx,
                                       kv + float(sid), vv))
        pool.free(2)  # hole in the middle
        moved = pool.defrag()
        assert moved > 0
        # live pages are packed into the lowest physical indices
        live = sorted(p for sid in (1, 3) for p in pool.table(sid).pages)
        assert live == list(range(1, 7))
        assert pool.free_pages == 4
        for sid in (1, 3):
            kv, _ = gather_views(pool.k, pool.v, pool.gather_indices([sid]))
            assert np.allclose(np.asarray(kv)[:, :, :12], float(sid))
        assert pool.defrag() == 0  # idempotent once compact

    def test_validation(self):
        with pytest.raises(ValueError, match="multiple"):
            KVCachePool(num_layers=1, num_heads=1, head_dim=2, num_pages=4,
                        page_size=5, max_seq_len=16)
        pool = self.make()
        with pytest.raises(ValueError, match="max_seq_len"):
            pool.alloc(1, 17)
        pool.alloc(1, 1)
        with pytest.raises(ValueError, match="already"):
            pool.alloc(1, 1)


# ------------------------------------------------------- sampling helpers

class TestSampling:
    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 0.0]])
        assert list(np.asarray(greedy_sample(logits))) == [1, 0]
        assert greedy_sample(logits).dtype == jnp.int32

    def test_deterministic_under_fixed_key(self):
        """Property test: every draw is a pure function of (logits, key)."""
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((4, 33)), jnp.float32)
        for fn in (lambda k: temperature_sample(logits, 0.8, key=k),
                   lambda k: top_k_sample(logits, 7, 0.8, key=k)):
            draws = {}
            for seed in range(8):
                key = jax.random.PRNGKey(seed)
                a, b = fn(key), fn(key)
                assert np.array_equal(np.asarray(a), np.asarray(b))
                draws[seed] = tuple(np.asarray(a))
            # different keys must not all collapse to one draw
            assert len(set(draws.values())) > 1

    def test_top_k_support(self):
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.standard_normal((64, 20)), jnp.float32)
        top3 = np.asarray(jax.lax.top_k(logits, 3)[1])
        toks = np.asarray(top_k_sample(logits, 3, 1.5,
                                       key=jax.random.PRNGKey(4)))
        for row in range(64):
            assert toks[row] in top3[row]

    def test_top_k_larger_than_vocab_is_clamped(self):
        logits = jnp.asarray(np.random.default_rng(0).standard_normal(
            (4, 9)), jnp.float32)
        key = jax.random.PRNGKey(3)
        toks = np.asarray(top_k_sample(logits, 999, 1.0, key=key))  # no crash
        assert np.array_equal(
            toks, np.asarray(top_k_sample(logits, 9, 1.0, key=key)))
        assert ((0 <= toks) & (toks < 9)).all()

    def test_zero_temperature_collapses_to_greedy(self):
        logits = jnp.asarray([[0.0, 5.0, 1.0]])
        for fn in (temperature_sample, lambda lg, t, key: top_k_sample(
                lg, 2, t, key=key)):
            out = fn(logits, 0.0, key=jax.random.PRNGKey(0))
            assert list(np.asarray(out)) == [1]


# ------------------------------------------------- decode parity guarantees

class TestDecodeParity:
    def test_attention_incremental_matches_full(self):
        """dot_product_attention(causal) == token-by-token decode_attention
        through a ragged-offset KV cache, at fp32."""
        rng = np.random.default_rng(2)
        b, h, d, max_len = 3, 2, 4, 16
        lens = [7, 12, 3]
        q = jnp.asarray(rng.standard_normal((b, max_len, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, max_len, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, max_len, h, d)), jnp.float32)
        full = np.asarray(dot_product_attention(q, k, v, causal=True))
        k_cache = jnp.zeros((b, max_len, h, d))
        v_cache = jnp.zeros((b, max_len, h, d))
        got = np.zeros_like(full)
        for t in range(max_len):
            # ragged: row i stops appending at lens[i]; later steps re-run
            # earlier positions to exercise differing cache offsets
            offs = jnp.asarray([min(t, n - 1) for n in lens], jnp.int32)
            kn = jnp.stack([k[i, int(offs[i])][None] for i in range(b)])
            vn = jnp.stack([v[i, int(offs[i])][None] for i in range(b)])
            qn = jnp.stack([q[i, int(offs[i])][None] for i in range(b)])
            k_cache = ragged_cache_update(k_cache, kn, offs)
            v_cache = ragged_cache_update(v_cache, vn, offs)
            out = np.asarray(decode_attention(qn, k_cache, v_cache, offs))
            for i in range(b):
                got[i, int(offs[i])] = out[i, 0]
        for i, n in enumerate(lens):
            np.testing.assert_allclose(got[i, :n], full[i, :n],
                                       rtol=1e-5, atol=1e-5)

    def test_gpt_prefill_plus_incremental_matches_full(self):
        """Ragged batched prefill + one-token decode steps reproduce the
        full forward logits (fp32 allclose) at every generated position."""
        m = tiny_gpt()
        cfg = m.config
        rng = np.random.default_rng(3)
        lens = [5, 9, 2]
        prompts = [rng.integers(0, cfg.vocab_size, n) for n in lens]
        b, max_len, h = 3, 32, cfg.num_heads
        hd = cfg.hidden_size // h
        bucket = 16
        toks = np.zeros((b, bucket), np.int32)
        for i, p in enumerate(prompts):
            toks[i, :len(p)] = p
        kv = [(jnp.zeros((b, max_len, h, hd)),
               jnp.zeros((b, max_len, h, hd))) for _ in range(cfg.num_layers)]
        logits, kv = m(jnp.asarray(toks), kv_cache=kv,
                       cache_index=jnp.zeros(b, jnp.int32),
                       seq_lengths=jnp.asarray(lens, jnp.int32))
        seqs = [list(p) for p in prompts]
        for step in range(4):
            nxt = np.asarray(greedy_sample(logits))
            for i in range(b):
                seqs[i].append(int(nxt[i]))
            # reference: full forward over each row's entire sequence
            for i in range(b):
                ref = np.asarray(m(jnp.asarray(seqs[i])[None, :]))
                np.testing.assert_allclose(
                    np.asarray(logits)[i], ref[0, len(seqs[i]) - 2],
                    rtol=1e-5, atol=1e-5)
            offs = jnp.asarray([len(s) - 1 for s in seqs], jnp.int32)
            logits, kv = m(jnp.asarray(nxt[:, None]), kv_cache=kv,
                           cache_index=offs)


# ------------------------------------------------ read-only embedding cache

class TestReadOnlyCache:
    def test_push_raises_sync_serves(self):
        from hetu_tpu.embed.engine import CacheTable, HostEmbeddingTable
        table = HostEmbeddingTable(32, 4, optimizer="adam", seed=2)
        ro = CacheTable(table, 8, name="serve-ro", read_only=True)
        rows = ro.sync([1, 2, 3])
        assert rows.shape == (3, 4)
        np.testing.assert_allclose(rows, table.pull([1, 2, 3]))
        with pytest.raises(RuntimeError, match="read-only"):
            ro.push([1], np.ones((1, 4), np.float32))
        from hetu_tpu.embed.engine import AsyncEngine
        with pytest.raises(RuntimeError, match="read-only"):
            AsyncEngine(1).push_async(ro, [1], np.ones((1, 4), np.float32))
        ro.flush()  # empty buffer, must not raise
        st = ro.stats()
        assert st["misses"] >= 3
        # a writable cache over the same table still trains
        rw = CacheTable(table, 8, name="serve-rw")
        rw.push([1], np.ones((1, 4), np.float32))
        rw.flush()

    def test_mark_read_only_flushes_buffered_pushes_first(self):
        """A model that trained with push_bound buffering must have its
        pending gradient writebacks applied BEFORE the freeze — flipping
        read_only must not silently drop the tail of training."""
        from hetu_tpu.core.module import Module
        from hetu_tpu.embed import StagedHostEmbedding
        emb = StagedHostEmbedding(16, 4, cache_capacity=8, push_bound=10,
                                  optimizer="sgd", lr=1.0, seed=7)
        emb.stage([1, 2])
        before = emb.table.pull([1, 2]).copy()
        emb.push_grads(np.ones((2, 4), np.float32))  # buffered, not applied
        np.testing.assert_allclose(emb.table.pull([1, 2]), before)

        class Wrap(Module):
            def __init__(self):
                self.embed = emb

        ServingEngine(tiny_gpt(), num_slots=1, page_size=8, max_seq_len=32,
                      ctr_model=Wrap())
        # the freeze drained the buffer: sgd applied lr * grad = 1.0
        np.testing.assert_allclose(emb.table.pull([1, 2]), before - 1.0,
                                   rtol=1e-6)
        assert emb.store.read_only is True

    def test_engine_marks_ctr_stores_read_only(self):
        from hetu_tpu.models.ctr import CTRConfig, WideDeep
        set_random_seed(0)
        ctr = WideDeep(CTRConfig(
            dense_dim=4, sparse_fields=3, vocab=50, embed_dim=4,
            mlp_hidden=16, embedding="host", host_bridge="staged",
            cache_capacity=16))
        assert ctr.embed.store.read_only is False
        eng = ServingEngine(tiny_gpt(), num_slots=2, page_size=8,
                            max_seq_len=32, ctr_model=ctr)
        assert ctr.embed.store.read_only is True
        pred = eng.infer_ctr(np.zeros((2, 4), np.float32),
                             [[1, 2, 3], [4, 5, 6]])
        assert pred.shape == (2,) and np.all((pred > 0) & (pred < 1))
        with pytest.raises(RuntimeError, match="read-only"):
            ctr.embed.store.push([1], np.zeros((1, 4), np.float32))


# ------------------------------------------------------ obs route table

class TestRoutes:
    def test_custom_route_registration(self):
        from hetu_tpu.obs.server import Routes, RoutedHTTPServer
        routes = Routes()
        routes.add("GET", "/ping", lambda q, b: b'{"pong": true}')
        routes.add("POST", "/echo", lambda q, b: (b, "text/plain"))
        routes.add("GET", "/boom", lambda q, b: 1 / 0)
        with RoutedHTTPServer(routes) as srv:
            srv.start()
            with urllib.request.urlopen(srv.url + "/ping", timeout=10) as r:
                assert json.loads(r.read())["pong"] is True
            req = urllib.request.Request(srv.url + "/echo", data=b"hello",
                                         method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.read() == b"hello"
            for path, code in (("/nope", 404), ("/ping", 405), ("/boom", 500)):
                try:
                    if code == 405:
                        urllib.request.urlopen(urllib.request.Request(
                            srv.url + path, data=b"", method="POST"),
                            timeout=10)
                    else:
                        urllib.request.urlopen(srv.url + path, timeout=10)
                    pytest.fail(f"expected HTTP {code} for {path}")
                except urllib.error.HTTPError as e:
                    assert e.code == code
                    if code == 500:
                        assert "division" in json.loads(
                            e.read())["error"]
        assert "/ping" in routes.paths()

    def test_telemetry_routes_still_served(self):
        with obs.serve() as srv:
            with urllib.request.urlopen(srv.url + "/healthz",
                                        timeout=10) as r:
                assert json.loads(r.read())["status"] == "ok"


# ---------------------------------------------------------------- batcher

class TestBatcher:
    def req(self, i, plen=4, now=0.0, deadline=None, max_new=4):
        return Request(id=i, prompt=list(range(plen)), max_new_tokens=max_new,
                       arrival=now, deadline_s=deadline)

    def test_queue_depth_limit(self):
        b = ContinuousBatcher(1, queue_depth=2)
        b.submit(self.req(0))
        b.submit(self.req(1))
        with pytest.raises(AdmissionQueueFull):
            b.submit(self.req(2))

    def test_fifo_admission_and_recycle(self):
        b = ContinuousBatcher(2, queue_depth=8)
        for i in range(4):
            b.submit(self.req(i))
        tick = b.poll(0.0)
        assert [r.id for r in tick.admitted] == [0, 1]
        assert b.active_slots == 2 and b.queue_len == 2
        assert b.finish(0).id == 0
        tick = b.poll(0.0)
        assert [r.id for r in tick.admitted] == [2]
        assert [s for s, _ in b.active()] == [0, 1]
        assert b.active()[0][1].id == 2  # recycled into the freed slot

    def test_deadline_expiry_and_capacity_gate(self):
        b = ContinuousBatcher(1, queue_depth=8)
        b.submit(self.req(0))
        b.submit(self.req(1, deadline=0.5))
        b.submit(self.req(2))
        tick = b.poll(0.0)
        assert [r.id for r in tick.admitted] == [0]
        tick = b.poll(1.0)  # slot busy; request 1 blows its deadline
        assert [r.id for r in tick.expired] == [1]
        b.finish(0)
        # FIFO preserved under a capacity gate: request 2 refused -> stop
        tick = b.poll(1.0, can_admit=lambda r: False)
        assert tick.admitted == [] and b.queue_len == 1

    def test_bucket_for(self):
        b = ContinuousBatcher(1, prompt_buckets=(8, 32))
        assert b.bucket_for(3) == 8 and b.bucket_for(8) == 8
        assert b.bucket_for(9) == 32
        with pytest.raises(ValueError, match="largest bucket"):
            b.bucket_for(33)


# ------------------------------------------------- engine scheduling paths

class TestEngineScheduling:
    def test_rejection_and_deadline_telemetry(self):
        reg = obs.get_registry()
        clk = VirtualClock()
        journal = obs.EventJournal()
        m = tiny_gpt()
        with obs.use(journal):
            eng = ServingEngine(m, num_slots=1, page_size=8, max_seq_len=32,
                                prompt_buckets=(8,), queue_depth=1,
                                seed=0, clock=clk)
            s0 = reg.snapshot()
            running = eng.submit([1, 2, 3], 24)        # occupies the slot
            eng.step()
            waiting = eng.submit([4, 5], 4, deadline_s=0.5)  # queued
            overflow = eng.submit([6], 4)              # queue full -> reject
            assert overflow.done and overflow.status == "rejected"
            clk.advance(1.0)                           # waiting one expires
            eng.step()
            assert waiting.done and waiting.status == "expired"
            eng.run_until_idle()
            assert running.status == "completed"
            d = reg.delta(reg.snapshot(), s0)
        assert d['hetu_serve_requests_total{outcome="rejected"}'] == 1
        assert d['hetu_serve_requests_total{outcome="expired"}'] == 1
        # only the running request ever reached a slot
        assert d['hetu_serve_requests_total{outcome="admitted"}'] == 1
        assert d['hetu_serve_requests_total{outcome="completed"}'] == 1
        kinds = [e["kind"] for e in journal.events]
        assert "serve_reject" in kinds and "request_expired" in kinds
        rej = journal.of_kind("serve_reject")[0]
        assert rej["request_id"] == overflow.request_id
        exp = journal.of_kind("request_expired")[0]
        assert exp["stage"] == "queued" and exp["waited_s"] >= 0.5
        # the deadline satellite: expiries are counted by stage, not
        # silently dropped, and the handle names why it failed
        assert d['hetu_serve_deadline_expired_total{stage="queued"}'] == 1
        assert waiting.error is not None and "deadline" in waiting.error
        assert overflow.error is not None  # rejection reason rides too

    def test_eos_recycles_slot_early(self):
        m = tiny_gpt()
        clk = VirtualClock()
        # probe: discover the greedy continuation to use as EOS
        probe = ServingEngine(m, num_slots=1, page_size=8, max_seq_len=32,
                              prompt_buckets=(8,), clock=clk)
        h = probe.submit([1, 2, 3], 3)
        probe.run_until_idle()
        eos = h.tokens[0]
        eng = ServingEngine(m, num_slots=1, page_size=8, max_seq_len=32,
                            prompt_buckets=(8,), eos_id=eos, clock=clk)
        h2 = eng.submit([1, 2, 3], 24)
        eng.run_until_idle()
        assert h2.status == "completed"
        assert h2.tokens[-1] == eos and len(h2.tokens) < 24
        assert eng.pool.live_sequences == 0  # pages freed on EOS

    def test_too_long_prompt_rejected(self):
        eng = ServingEngine(tiny_gpt(), num_slots=1, page_size=8,
                            max_seq_len=32, prompt_buckets=(8, 32))
        h = eng.submit(list(range(30)), 8)  # 30 + 8 > 32
        assert h.done and h.status == "rejected"
        # a prompt over the largest prefill bucket must be rejected at
        # submit, not crash the scheduler at bucket_for()
        m = tiny_gpt()
        eng = ServingEngine(m, num_slots=1, page_size=8, max_seq_len=64,
                            prompt_buckets=(8,))
        h = eng.submit(list(range(20)), 4)  # 24 <= 64 but bucket max is 8
        assert h.done and h.status == "rejected"
        ok = eng.submit([1, 2, 3], 2)
        eng.run_until_idle()  # the loop survived and serves the next one
        assert ok.status == "completed"

    def test_invalid_sampling_mode_raises(self):
        with pytest.raises(ValueError, match="sampling mode"):
            ServingEngine(tiny_gpt(), sampling="nucleus")
        with pytest.raises(ValueError, match="top_k must be"):
            ServingEngine(tiny_gpt(), sampling="top_k", top_k=0)

    def test_nonpositive_token_budget_rejected(self):
        eng = ServingEngine(tiny_gpt(), num_slots=1, page_size=8,
                            max_seq_len=32, prompt_buckets=(8,))
        for bad in (0, -3):
            h = eng.submit([1, 2], bad)
            assert h.done and h.status == "rejected" and h.tokens == []

    def test_temperature_mode_is_not_topk_truncated(self):
        """sampling='temperature' must draw from the full distribution,
        not a silently top-k-truncated one."""
        m = tiny_gpt()

        def collect(mode):
            eng = ServingEngine(m, num_slots=2, page_size=8, max_seq_len=64,
                                prompt_buckets=(8,), sampling=mode, top_k=1,
                                temperature=3.0, seed=0)
            hs = [eng.submit([i + 1, i + 2], 8) for i in range(8)]
            eng.run_until_idle()
            return [t for h in hs for t in h.tokens]

        # top_k=1 at any temperature is greedy-like: few distinct tokens;
        # full-temperature sampling at T=3 must show more diversity
        assert len(set(collect("temperature"))) > len(set(collect("top_k")))

    def test_overcommitted_pool_evicts_instead_of_wedging(self):
        """With num_pages below full per-slot capacity (explicit
        overcommit), decode growth past the pool retires the victim with
        the tokens it has ('evicted') instead of killing the loop."""
        m = tiny_gpt()
        # 2 slots x (32/8)=4 pages full capacity = 8+scratch; give only 6
        eng = ServingEngine(m, num_slots=2, page_size=8, max_seq_len=32,
                            prompt_buckets=(8,), num_pages=7, seed=0)
        h1 = eng.submit([1, 2, 3, 4, 5, 6, 7], 24)   # wants 31 tokens
        h2 = eng.submit([8, 9, 10, 11, 12, 13], 24)  # wants 30 tokens
        eng.run_until_idle()
        statuses = sorted([h1.status, h2.status])
        assert "evicted" in statuses           # somebody hit the wall...
        assert eng.pool.live_sequences == 0    # ...and everything drained
        for h in (h1, h2):
            assert h.done and len(h.tokens) > 0


# -------------------------------------------------- /infer endpoint smoke

def _valid_prom_line(line):
    comment = re.compile(r"^# (HELP [a-zA-Z_:][a-zA-Z0-9_:]* .*"
                         r"|TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                         r"(counter|gauge|histogram|summary|untyped))$")
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*")*\})?'
        r' (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$')
    return bool(comment.match(line) or sample.match(line))


def test_infer_endpoint_live_engine():
    """Satellite smoke: /infer against a live ServingEngine on a tiny GPT,
    response fields validated, and the shared-port /metrics exposition
    line-validated — the serving mirror of test_obs's /metrics smoke."""
    eng = ServingEngine(tiny_gpt(), num_slots=2, page_size=8, max_seq_len=32,
                        prompt_buckets=(8, 16), seed=1)
    srv = serve_engine(eng)
    try:
        body = json.dumps({"prompt": [5, 6, 7], "max_new_tokens": 4,
                           "timeout_s": 120}).encode()
        req = urllib.request.Request(srv.url + "/infer", data=body,
                                     method="POST")
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
            out = json.loads(r.read())
        assert out["status"] == "completed"
        assert len(out["tokens"]) == 4
        assert all(0 <= t < 97 for t in out["tokens"])
        assert out["ttft_s"] >= 0 and out["latency_s"] >= out["ttft_s"]
        with urllib.request.urlopen(srv.url + "/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["active_slots"] == 0
        assert stats["pool"]["pages_used"] == 0
        assert any(k.startswith("hetu_serve_requests_total")
                   for k in stats["metrics"])
        # SLO summary: TTFT quantiles through Histogram.quantile — the
        # request above observed at least one TTFT, so p50 <= p99
        slo = stats["slo"]
        assert set(slo) == {"ttft_p50_s", "ttft_p99_s",
                            "token_latency_p50_s", "token_latency_p99_s"}
        assert slo["ttft_p50_s"] is not None
        assert slo["ttft_p50_s"] <= slo["ttft_p99_s"]
        with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for line in text.splitlines():
            assert _valid_prom_line(line), f"invalid exposition: {line!r}"
        assert "hetu_serve_ttft_seconds_bucket" in text
        assert 'hetu_serve_requests_total{outcome="completed"}' in text
    finally:
        srv.stop()
        eng.stop()


# ------------------------------------------------ sustained-load acceptance

def _run_trace(model, trace, seed, **engine_kw):
    """Drive a full load trace on a virtual clock; returns (token streams,
    handle statuses, registry delta, pool)."""
    reg = obs.get_registry()
    clk = VirtualClock()
    eng = ServingEngine(model, seed=seed, clock=clk, **engine_kw)
    s0 = reg.snapshot()
    handles, i = {}, 0
    while i < len(trace) or not eng.batcher.idle:
        while i < len(trace) and trace[i].submit_at <= clk.t:
            handles[i] = eng.submit(list(trace[i].prompt),
                                    trace[i].max_new_tokens,
                                    deadline_s=trace[i].deadline_s)
            i += 1
        eng.step()
        clk.advance(0.001)
    streams = {j: tuple(h.tokens) for j, h in handles.items()}
    status = {j: h.status for j, h in handles.items()}
    return streams, status, reg.delta(reg.snapshot(), s0), eng.pool


def test_sustained_load_acceptance():
    """Acceptance: >= 64 seeded concurrent requests with mixed prompt
    lengths through the continuous batcher — zero dropped, exact obs
    counters, and token streams bitwise-identical across two same-seed
    runs (defrag running underneath)."""
    model = tiny_gpt()
    trace = generate_load(17, 64, vocab=97, prompt_len=(2, 20),
                          max_new=(1, 8), mean_gap_s=0.0005)
    assert len({len(t.prompt) for t in trace}) > 5  # genuinely mixed
    kw = dict(num_slots=8, page_size=8, max_seq_len=64,
              prompt_buckets=(8, 16, 32), queue_depth=64,
              sampling="top_k", top_k=5, defrag_every=5)
    streams1, status1, d1, pool1 = _run_trace(model, trace, seed=11, **kw)
    streams2, status2, d2, pool2 = _run_trace(model, trace, seed=11, **kw)

    # zero dropped requests
    assert len(status1) == 64
    assert set(status1.values()) == {"completed"}
    # exact accounting: every admitted request completed, nothing else
    for d in (d1, d2):
        assert d['hetu_serve_requests_total{outcome="admitted"}'] == 64
        assert d['hetu_serve_requests_total{outcome="completed"}'] == 64
        assert d.get('hetu_serve_requests_total{outcome="rejected"}', 0) == 0
        assert d.get('hetu_serve_requests_total{outcome="expired"}', 0) == 0
        assert d["hetu_serve_tokens_total"] == sum(
            len(s) for s in streams1.values())
    # every request got exactly its token budget (no EOS configured)
    for j, item in enumerate(trace):
        assert len(streams1[j]) == item.max_new_tokens
    # bitwise-identical streams across same-seed runs
    assert streams1 == streams2
    # and the pool drained completely both times
    assert pool1.live_sequences == 0 and pool2.live_sequences == 0
    assert pool1.free_pages == pool1.num_pages - 1

    # a different sampling seed must actually change some stream (the
    # determinism above is seed-derived, not an accident of greedy ties)
    streams3, _, _, _ = _run_trace(model, trace, seed=12, **kw)
    assert streams3 != streams1


@pytest.mark.chaos
def test_ctr_chaos_ps_timeout_is_counted_retry():
    """Chaos acceptance: an injected PS socket kill during read-only CTR
    serving surfaces as exactly one counted redial — and the predictions
    are bitwise identical to the clean run's."""
    from hetu_tpu.embed.net import EmbeddingServer, RemoteHostEmbedding
    from hetu_tpu.layers import Linear
    from hetu_tpu.core.module import Module
    reg = obs.get_registry()

    rng = np.random.default_rng(5)
    dense = np.asarray(rng.standard_normal((6, 4)), np.float32)
    sparse = rng.integers(0, 60, (6, 3))

    def run(table_id, plan_events):
        with EmbeddingServer() as srv:
            set_random_seed(0)

            class M(Module):
                def __init__(self):
                    self.embed = RemoteHostEmbedding(
                        60, 4, servers=[f"127.0.0.1:{srv.port}"],
                        table_id=table_id, seed=5, reconnect_attempts=5,
                        reconnect_backoff=0.01)
                    self.head = Linear(12, 1)

                def logits(self, d, sp):
                    e = self.embed(sp).reshape(sp.shape[0], -1)
                    return self.head(e)[:, 0]

            m = M()
            eng = ServingEngine(tiny_gpt(), num_slots=1, page_size=8,
                                max_seq_len=32, ctr_model=m)
            s0 = reg.snapshot()
            preds = []
            with faults.inject(faults.FaultPlan(plan_events)) as plan:
                for step in range(1, 4):
                    plan.advance(step)
                    preds.append(eng.infer_ctr(dense, sparse))
                assert plan.remaining() == []
            return np.stack(preds), reg.delta(reg.snapshot(), s0)

    clean, d_clean = run(901, [])
    chaos, d_chaos = run(902, [(2, "ps_socket_kill")])

    # the timeout surfaced as a counted retry...
    redials = sum(v for k, v in d_chaos.items()
                  if k.startswith("hetu_ps_redials_total"))
    dead = sum(v for k, v in d_chaos.items()
               if k.startswith('hetu_ps_rpc_errors_total{type="dead_socket"'))
    assert redials == 1 and dead == 1
    assert sum(v for k, v in d_clean.items()
               if k.startswith("hetu_ps_redials_total")) == 0
    # ...not a wrong answer
    np.testing.assert_array_equal(clean, chaos)
    assert d_chaos["hetu_serve_ctr_requests_total"] == 3
