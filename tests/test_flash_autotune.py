"""Flash block autotuner: measured cache entries outrank the heuristic.

The hand-swept `_auto_blocks` table only covers the shapes past rounds
measured (head_dim 64 + two d=128 points); ``autotune_flash_blocks``
makes any (seq, head_dim, device-kind) combination measurable on the spot
and persists the winner.  These tests run the REAL tuner in interpreter
mode on a tiny shape (end-to-end: measurement, persistence, atomic write)
and pin the trace-time lookup priority: explicit args > tuned cache >
heuristic.
"""

import json

import jax.numpy as jnp
import pytest

from hetu_tpu.ops.pallas import autotune as at
from hetu_tpu.ops.pallas.flash import _auto_blocks, _block_sizes


@pytest.fixture
def tune_cache(tmp_path, monkeypatch):
    path = tmp_path / "flash_blocks.json"
    monkeypatch.setenv(at._CACHE_ENV, str(path))
    at.clear_tune_cache()
    yield path
    at.clear_tune_cache()


# slow tier (r5 re-tier pass 2): the cache-priority + kernel-feed tests stay fast; this runs the real tuner in the interpreter
@pytest.mark.slow
def test_autotune_runs_and_persists(tune_cache):
    entry = at.autotune_flash_blocks(
        8, 8, 4, causal=True, batch=1, heads=1, dtype=jnp.float32,
        interpret=True, n1=1, n2=2)
    assert entry["block_q"] in (4, 8) and entry["block_k"] in (4, 8)
    assert any(isinstance(v, float) for v in entry["table"].values())
    # persisted, and the file is valid json with the device-kind key
    disk = json.loads(tune_cache.read_text())
    (key,) = disk.keys()
    assert "|8x8|d4|c1" in key
    # the lookup sees it (and the causal-complement fallback works)
    assert at.tuned_blocks(8, 8, 4, causal=True) == (
        entry["block_q"], entry["block_k"])
    assert at.tuned_blocks(8, 8, 4, causal=False) == (
        entry["block_q"], entry["block_k"])
    assert at.tuned_blocks(16, 16, 4, causal=True) is None


def test_alignment_validated_up_front(tune_cache):
    """Satellite: on TPU (interpret=False), a sequence that is not a
    multiple of 128 must be rejected immediately with the constraint
    named — not after the whole candidate grid comes back empty as the
    baffling 'no flash block candidate ran: {}'."""
    for Sq, Sk in ((100, 128), (128, 100), (64, 64), (384, 200)):
        with pytest.raises(ValueError, match="multiples\\s*of 128") as ei:
            at.autotune_flash_blocks(Sq, Sk, 64, interpret=False)
        assert f"Sq={Sq}" in str(ei.value)  # names the offending shape
    # an aligned shape sails past the validation (and into measurement,
    # which we stub out — the real sweep is the slow test's job)
    with pytest.raises(RuntimeError, match="no flash block candidate"):
        at.autotune_flash_blocks(
            128, 128, 64, interpret=False, save=False, budget_s=-1.0)


def test_complement_fallback_tagged_and_superseded(tune_cache,
                                                   monkeypatch):
    """Satellite: a complement-mask cache fallback is tagged in the
    in-memory cache (identifiable as a borrowed measurement, never
    persisted), and a later exact-mask tune supersedes it."""
    key_c1 = at._key(8, 8, 4, True, None)
    key_c0 = at._key(8, 8, 4, False, None)
    tune_cache.write_text(json.dumps(
        {key_c1: {"block_q": 8, "block_k": 8}}))
    at.clear_tune_cache()
    # exact miss, complement hit: returned AND tagged under the exact key
    assert at.tuned_blocks(8, 8, 4, causal=False) == (8, 8)
    assert at._load()[key_c0]["complement_fallback"] is True
    # repeat lookups hit the tagged memo, same answer
    assert at.tuned_blocks(8, 8, 4, causal=False) == (8, 8)
    # the tag never reaches disk
    assert key_c0 not in json.loads(tune_cache.read_text())
    # a later exact-mask tune supersedes: run the real tuner with only
    # the timer stubbed (the interpret-mode kernel sweep is the slow
    # test's job) — its save path merges against disk and drops the
    # memoized tag
    monkeypatch.setattr(
        at, "_time_fwd_bwd",
        lambda bq, bk, *a, **kw: 1.0 if (bq, bk) != (4, 4) else 0.5)
    entry = at.autotune_flash_blocks(8, 8, 4, causal=False, batch=1,
                                     heads=1, dtype=jnp.float32,
                                     interpret=True, n1=1, n2=2)
    assert (entry["block_q"], entry["block_k"]) == (4, 4)
    assert at.tuned_blocks(8, 8, 4, causal=False) == (4, 4)
    assert "complement_fallback" not in at._load()[key_c0]
    # the complement (causal=True) entry still answers exactly
    assert at.tuned_blocks(8, 8, 4, causal=True) == (8, 8)


def test_block_sizes_priority(tune_cache):
    # seed a fake measured entry
    tune_cache.write_text(json.dumps({
        at._key(256, 256, 64, False, None): {"block_q": 256, "block_k": 128},
    }))
    at.clear_tune_cache()
    heur = _auto_blocks(256, 256, 64)
    assert (256, 128) != heur  # the test must distinguish cache from table
    # tuned cache outranks the heuristic...
    assert _block_sizes(256, 256, 64, None, None, True) == (256, 128)
    # ...explicit args outrank the cache (per-axis)
    assert _block_sizes(256, 256, 64, 64, None, True) == (64, 128)
    # uncached shapes fall through to the heuristic
    s = 512
    assert _block_sizes(s, s, 64, None, None, True) == \
        tuple(min(b, s) for b in _auto_blocks(s, s, 64))


def test_tuner_feeds_flash_attention_bhsd(tune_cache):
    """End to end: a tuned entry changes the blocks the kernel entry uses
    (observable because mis-dividing blocks would raise; here we check via
    the interpret path running fine with the tuned 4x4 on an 8-seq)."""
    import numpy as np

    from hetu_tpu.ops.pallas.flash import flash_attention_bhsd

    tune_cache.write_text(json.dumps({
        at._key(8, 8, 4, True, None): {"block_q": 4, "block_k": 4},
    }))
    at.clear_tune_cache()
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 1, 8, 4)), jnp.float32)
               for _ in range(3))
    out = flash_attention_bhsd(q, k, v, causal=True, interpret=True)
    assert out.shape == (1, 1, 8, 4)
