"""Flash block autotuner: measured cache entries outrank the heuristic.

The hand-swept `_auto_blocks` table only covers the shapes past rounds
measured (head_dim 64 + two d=128 points); ``autotune_flash_blocks``
makes any (seq, head_dim, device-kind) combination measurable on the spot
and persists the winner.  These tests run the REAL tuner in interpreter
mode on a tiny shape (end-to-end: measurement, persistence, atomic write)
and pin the trace-time lookup priority: explicit args > tuned cache >
heuristic.
"""

import json

import jax.numpy as jnp
import pytest

from hetu_tpu.ops.pallas import autotune as at
from hetu_tpu.ops.pallas.flash import _auto_blocks, _block_sizes


@pytest.fixture
def tune_cache(tmp_path, monkeypatch):
    path = tmp_path / "flash_blocks.json"
    monkeypatch.setenv(at._CACHE_ENV, str(path))
    at.clear_tune_cache()
    yield path
    at.clear_tune_cache()


# slow tier (r5 re-tier pass 2): the cache-priority + kernel-feed tests stay fast; this runs the real tuner in the interpreter
@pytest.mark.slow
def test_autotune_runs_and_persists(tune_cache):
    entry = at.autotune_flash_blocks(
        8, 8, 4, causal=True, batch=1, heads=1, dtype=jnp.float32,
        interpret=True, n1=1, n2=2)
    assert entry["block_q"] in (4, 8) and entry["block_k"] in (4, 8)
    assert any(isinstance(v, float) for v in entry["table"].values())
    # persisted, and the file is valid json with the device-kind key
    disk = json.loads(tune_cache.read_text())
    (key,) = disk.keys()
    assert "|8x8|d4|c1" in key
    # the lookup sees it (and the causal-complement fallback works)
    assert at.tuned_blocks(8, 8, 4, causal=True) == (
        entry["block_q"], entry["block_k"])
    assert at.tuned_blocks(8, 8, 4, causal=False) == (
        entry["block_q"], entry["block_k"])
    assert at.tuned_blocks(16, 16, 4, causal=True) is None


def test_block_sizes_priority(tune_cache):
    # seed a fake measured entry
    tune_cache.write_text(json.dumps({
        at._key(256, 256, 64, False, None): {"block_q": 256, "block_k": 128},
    }))
    at.clear_tune_cache()
    heur = _auto_blocks(256, 256, 64)
    assert (256, 128) != heur  # the test must distinguish cache from table
    # tuned cache outranks the heuristic...
    assert _block_sizes(256, 256, 64, None, None, True) == (256, 128)
    # ...explicit args outrank the cache (per-axis)
    assert _block_sizes(256, 256, 64, 64, None, True) == (64, 128)
    # uncached shapes fall through to the heuristic
    s = 512
    assert _block_sizes(s, s, 64, None, None, True) == \
        tuple(min(b, s) for b in _auto_blocks(s, s, 64))


def test_tuner_feeds_flash_attention_bhsd(tune_cache):
    """End to end: a tuned entry changes the blocks the kernel entry uses
    (observable because mis-dividing blocks would raise; here we check via
    the interpret path running fine with the tuned 4x4 on an 8-seq)."""
    import numpy as np

    from hetu_tpu.ops.pallas.flash import flash_attention_bhsd

    tune_cache.write_text(json.dumps({
        at._key(8, 8, 4, True, None): {"block_q": 4, "block_k": 4},
    }))
    at.clear_tune_cache()
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 1, 8, 4)), jnp.float32)
               for _ in range(3))
    out = flash_attention_bhsd(q, k, v, causal=True, interpret=True)
    assert out.shape == (1, 1, 8, 4)
