"""Serving-fleet tests: copy-on-write prefix sharing, speculative
decoding, cache-affinity routing (hetu_tpu/serve/fleet/).

Tier-1: the refcount/CoW pool contract, the never-alias property test
(hash collisions degrade to misses), bitwise speculative-vs-baseline
stream equality across all three sampling modes, the zero-duplicate-
prefix-page acceptance, router placement policy + bounded retries, the
2-replica endpoint smoke, and the full-fleet same-seed replay (bitwise
placements / streams / journal).  The wall-clock fleet-vs-single perf
comparison and the multi-replica shed/freeze chaos run ride the slow
tier.
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from hetu_tpu.core import set_random_seed
from hetu_tpu.models import GPT
from hetu_tpu.models.gpt import GPTConfig
from hetu_tpu.obs import journal as obs_journal
from hetu_tpu.obs import registry as obs_registry
from hetu_tpu.serve import (DoubleFree, FleetRouter, KVCachePool,
                            OutOfPages, ServingEngine,
                            generate_shared_prefix_load, serve_fleet_router)
from hetu_tpu.serve import kv_cache as kvmod
from hetu_tpu.serve.fleet import prefix as prefix_mod
from hetu_tpu.serve.fleet.prefix import PrefixSharer

pytestmark = [pytest.mark.serve, pytest.mark.fleet]

CFG = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=64)
DRAFT_CFG = GPTConfig(vocab_size=97, hidden_size=16, num_layers=1,
                      num_heads=2, max_seq_len=64)
TEMPLATE = tuple(range(1, 17))  # 16 tokens = 2 full pages at page_size 8


@pytest.fixture(scope="module")
def model():
    set_random_seed(0)
    return GPT(CFG)


@pytest.fixture(scope="module")
def draft(model):
    set_random_seed(1)
    return GPT(DRAFT_CFG)


class VirtualClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_engine(model, clock, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("prompt_buckets", (8, 16, 32))
    kw.setdefault("seed", 11)
    kw.setdefault("sampling", "greedy")
    return ServingEngine(model, clock=clock, **kw)


def drain(target, clock, max_steps: int = 5000) -> int:
    """Step an engine or router until idle on the virtual clock; returns
    scheduler ticks taken."""
    idle = (lambda: target.batcher.idle) if hasattr(target, "batcher") \
        else (lambda: target.idle)
    for i in range(max_steps):
        if idle():
            return i
        target.step()
        clock.advance(0.001)
    raise AssertionError(f"not idle after {max_steps} ticks")


def tiny_pool(**kw) -> KVCachePool:
    kw.setdefault("num_layers", 1)
    kw.setdefault("num_heads", 1)
    kw.setdefault("head_dim", 2)
    kw.setdefault("num_pages", 8)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq_len", 16)
    return KVCachePool(**kw)


class TestRefcountPool:
    def test_shared_alloc_aliases_and_refcounts(self):
        pool = tiny_pool()
        a = pool.alloc(0, 10)          # 3 pages, rc 1 each
        b = pool.alloc(1, 10, shared_pages=a.pages[:2])
        assert b.pages[:2] == a.pages[:2]          # aliased, not copied
        assert pool.refcount(a.pages[0]) == 2
        assert pool.stats()["pages_shared"] == 2
        pool.free(0)
        # shared pages survive A's retirement; A's private page freed
        assert pool.refcount(a.pages[0]) == 1
        assert pool.refcount(a.pages[2]) == 0
        pool.free(1)
        assert pool.stats()["pages_free"] == pool.num_pages - 1
        assert pool.stats()["allocs"] == 2 and pool.stats()["frees"] == 2

    def test_double_free_raises_named(self):
        pool = tiny_pool()
        pool.alloc(0, 4)
        pool.free(0)
        with pytest.raises(DoubleFree):
            pool.free(0)
        with pytest.raises(DoubleFree):
            pool.release(1)  # already on the free list
        pool.stats()  # invariants still hold after the refused frees

    def test_copy_on_write_unshares(self):
        pool = tiny_pool()
        a = pool.alloc(0, 8)
        pool.k = pool.k.at[:, a.pages[0]].set(7.0)
        pool.v = pool.v.at[:, a.pages[0]].set(3.0)
        b = pool.alloc(1, 8, shared_pages=a.pages[:1])
        assert pool.copy_on_write(1, 0) is True
        assert b.pages[0] != a.pages[0]            # B got a private copy
        assert pool.refcount(a.pages[0]) == 1
        assert np.all(np.asarray(pool.k[:, b.pages[0]]) == 7.0)
        assert np.all(np.asarray(pool.v[:, b.pages[0]]) == 3.0)
        # already-private pages never copy
        assert pool.copy_on_write(1, 0) is False
        pool.stats()

    def test_defrag_pins_shared_and_trie_held_pages(self):
        pool = tiny_pool(num_pages=12)
        a = pool.alloc(0, 12)                       # pages 1,2,3
        b = pool.alloc(1, 12, shared_pages=a.pages[:1])  # 1(shared),4,5
        pool.retain(a.pages[2])                     # "trie" holds page 3
        marker = {p: float(p) for pt in (a, b) for p in pt.pages}
        for p, val in marker.items():
            pool.k = pool.k.at[:, p].set(val)
        pool.free(0)   # pages 2 freed; 1 shared w/ B; 3 kept by the trie
        shared, trie_held = b.pages[0], a.pages[2]
        moved = pool.defrag()
        assert moved > 0
        # pinned pages kept their physical index
        assert b.pages[0] == shared and pool.refcount(trie_held) == 1
        # every surviving table entry still reads its own bytes (movable
        # pages' rows moved with the permutation, pinned ones stayed)
        for want, page in zip([marker[shared], 4.0, 5.0], b.pages):
            assert np.all(np.asarray(pool.k[:, page]) == want)
        pool.stats()

    def test_out_of_pages_on_shared_alloc_is_side_effect_free(self):
        pool = tiny_pool(num_pages=4)  # 3 usable
        a = pool.alloc(0, 8)           # 2 pages
        before = pool.stats()
        with pytest.raises(OutOfPages):
            pool.alloc(1, 16, shared_pages=a.pages[:2])  # needs 2 fresh
        assert pool.stats() == before


class TestPrefixTrie:
    def test_hash_collision_never_aliases(self, monkeypatch):
        # force EVERY block to the same hash bucket: token equality alone
        # must prevent aliasing
        monkeypatch.setattr(prefix_mod, "block_key", lambda block: 0)
        pool = tiny_pool(num_pages=16)
        sharer = PrefixSharer(pool)
        a_prompt = list(range(10))
        a = pool.alloc(0, len(a_prompt))
        sharer.publish(a_prompt, a)
        b_prompt = [9, 9, 9, 9] + a_prompt[4:]
        pages, shared = sharer.lookup(b_prompt)
        assert pages == [] and shared == 0
        # and publishing the colliding prompt must not overwrite A's node
        b = pool.alloc(1, len(b_prompt))
        sharer.publish(b_prompt, b)
        assert sharer.lookup(a_prompt + [50])[0] == [a.pages[0],
                                                     a.pages[1]]

    def test_property_differing_prompts_never_alias(self):
        # seeded property sweep: mutate one token anywhere inside the
        # shareable region; no aliased page may cover the mutation
        rng = np.random.default_rng(7)
        for trial in range(40):
            pool = tiny_pool(num_pages=16)
            sharer = PrefixSharer(pool)
            plen = int(rng.integers(5, 16))
            a_prompt = [int(t) for t in rng.integers(0, 97, plen)]
            a = pool.alloc(0, plen)
            sharer.publish(a_prompt, a)
            pos = int(rng.integers(0, plen))
            b_prompt = list(a_prompt)
            b_prompt[pos] = (b_prompt[pos] + 1 + int(rng.integers(96))) % 97
            pages, shared_tokens = sharer.lookup(b_prompt)
            # aliased pages must cover only block-equal prefixes
            assert shared_tokens <= (pos // 4) * 4, \
                (trial, a_prompt, b_prompt, pos, shared_tokens)
            for i, page in enumerate(pages):
                assert a_prompt[i * 4:(i + 1) * 4] == \
                    b_prompt[i * 4:(i + 1) * 4]
                assert page == a.pages[i]

    def test_eviction_reclaims_lru_trie_only_pages(self):
        pool = tiny_pool(num_pages=8)
        sharer = PrefixSharer(pool)
        p1 = [1] * 4 + [9]
        p2 = [2] * 4 + [9]
        for sid, prompt in ((0, p1), (1, p2)):
            t = pool.alloc(sid, len(prompt))
            sharer.publish(prompt, t)
            pool.free(sid)
        assert pool.stats()["pages_free"] == 5  # 2 pages live in the trie
        sharer.lookup(p1)  # bump p1's recency: p2 must evict first
        freed = sharer.reclaim(1)
        assert freed == 1
        assert sharer.lookup(p1 + [8])[1] == 4   # p1 survived
        assert sharer.lookup(p2 + [8])[1] == 0   # p2 evicted
        assert sharer.reclaim(5) == 1            # only p1's page remains
        assert pool.stats()["pages_free"] == 7


class TestSharedPrefixEngine:
    def test_zero_duplicate_prefix_pages_and_journal(self, model):
        clock = VirtualClock()
        eng = make_engine(model, clock, prefix_sharing=True)
        jr = obs_journal.EventJournal(clock=clock)
        with obs_journal.use(jr):
            h1 = eng.submit(list(TEMPLATE) + [40, 41], 4)
            drain(eng, clock)
            kvmod.reset_pages_written_count()
            h2 = eng.submit(list(TEMPLATE) + [50, 51, 52], 4)
            drain(eng, clock)
        assert h1.status == h2.status == "completed"
        # request 2: 19 prompt tokens = 3 pages, 2 aliased from the trie
        # -> ONE fresh (suffix) page written, zero duplicate prefix pages
        assert kvmod.pages_written_count() == 1
        shares = jr.of_kind("prefix_share")
        assert [e["shared_tokens"] for e in shares] == [16]
        assert shares[0]["request_id"] == h2.request_id

    def test_sharing_leaves_streams_unchanged(self, model):
        def run(prefix_sharing):
            clock = VirtualClock()
            eng = make_engine(model, clock, prefix_sharing=prefix_sharing)
            hs = [eng.submit(list(TEMPLATE) + [60 + i], 6)
                  for i in range(3)]
            drain(eng, clock)
            return [h.tokens for h in hs]

        assert run(True) == run(False)

    def test_share_trim_never_overflows_the_serving_window(self, model):
        """Regression: an untrimmed share of 40 tokens + a 32-token
        suffix bucket would ragged-write past the 64-token gathered view
        — dynamic_update_slice clamps, shifting the write back INTO the
        shared prefix pages and corrupting them for every alias.  The
        engine must trim the share until shared + suffix_bucket fits."""
        def run(sharing):
            clock = VirtualClock()
            eng = make_engine(model, clock, prefix_sharing=sharing,
                              prompt_buckets=(8, 16, 32, 64))
            a = list(range(1, 49))                    # publishes 6 blocks
            b = a[:40] + list(range(60, 80))          # 60 tokens, share 40
            c = a[:32] + [90]                         # re-aliases a's pages
            streams = []
            for p in (a, b, c):
                h = eng.submit(p, 3)
                drain(eng, clock)
                streams.append(h.tokens)
            return streams

        # corrupted shared pages would change b's own stream AND c's
        # (c re-reads the pages b's overflow would have clobbered)
        assert run(True) == run(False)

    def test_freeze_drops_sharing_instead_of_cold_suffix_compile(
            self, model):
        clock = VirtualClock()
        eng = make_engine(model, clock, prefix_sharing=True,
                          prompt_buckets=(8, 32))
        h1 = eng.submit(list(TEMPLATE) + [7] * 4, 3)   # warms bucket 32
        drain(eng, clock)
        assert eng._prefill_buckets == {32}
        eng.freeze_bucket_growth = True
        # share would leave a 4-token suffix -> bucket 8, COLD under the
        # freeze: prefill must drop the share and reuse the warm 32
        h2 = eng.submit(list(TEMPLATE) + [9] * 4, 3)
        drain(eng, clock)
        assert h2.status == "completed"
        assert eng._prefill_buckets == {32}  # no cold compile slipped in

    def test_admission_reclaims_trie_pages_under_pressure(self, model):
        clock = VirtualClock()
        # pool sized for exactly one max-length sequence per slot; the
        # trie's retained template pages must yield to real admissions
        eng = make_engine(model, clock, num_slots=2, num_pages=17,
                          prefix_sharing=True)
        h1 = eng.submit(list(TEMPLATE) + [7] * 14, 4)   # 30 tokens
        drain(eng, clock)
        handles = [eng.submit([80 + i] * 30, 4) for i in range(4)]
        drain(eng, clock)
        assert all(h.status == "completed" for h in handles)
        eng.pool.stats()


class TestSpeculative:
    @pytest.mark.parametrize("sampling", ["greedy", "temperature", "top_k"])
    def test_streams_bitwise_vs_baseline(self, model, draft, sampling):
        def run(draft_model):
            clock = VirtualClock()
            eng = make_engine(model, clock, sampling=sampling, top_k=5,
                              temperature=0.8, draft_model=draft_model,
                              spec_k=3)
            hs = [eng.submit(list(range(2 + i, 12 + i)), 8)
                  for i in range(4)]
            drain(eng, clock)
            return [(h.tokens, h.stream_fingerprint) for h in hs]

        assert run(draft) == run(None)

    def test_perfect_draft_accepts_and_saves_steps(self, model):
        reg = obs_registry.get_registry()

        def run(draft_model):
            clock = VirtualClock()
            eng = make_engine(model, clock, draft_model=draft_model,
                              spec_k=3)
            hs = [eng.submit(list(range(1 + i, 9 + i)), 12)
                  for i in range(4)]
            return [h.tokens for h in hs], drain(eng, clock)

        before = reg.snapshot()
        jr = obs_journal.EventJournal()
        with obs_journal.use(jr):
            spec_tokens, spec_steps = run(model)  # draft == target
        base_tokens, base_steps = run(None)
        assert spec_tokens == base_tokens
        assert spec_steps < base_steps  # k+1 tokens/slot/tick when accepted
        after = reg.snapshot()
        proposed = after.get("hetu_spec_proposed_tokens_total", 0) - \
            before.get("hetu_spec_proposed_tokens_total", 0)
        accepted = after.get("hetu_spec_accepted_tokens_total", 0) - \
            before.get("hetu_spec_accepted_tokens_total", 0)
        assert proposed > 0 and accepted == proposed  # greedy, same model
        events = jr.of_kind("spec_verify")
        assert events and all(e["accepted"] <= e["proposed"]
                              for e in events)

    def test_spec_requires_paged_decode(self, model, draft):
        with pytest.raises(ValueError, match="paged_decode"):
            make_engine(model, VirtualClock(), draft_model=draft,
                        paged_decode=False)

    def test_rejected_chains_leave_pool_consistent(self, model, draft):
        clock = VirtualClock()
        eng = make_engine(model, clock, sampling="top_k", top_k=5,
                          prefix_sharing=True, draft_model=draft,
                          spec_k=3)
        hs = [eng.submit(list(TEMPLATE) + [70 + i], 10) for i in range(5)]
        drain(eng, clock)
        assert all(h.status == "completed" for h in hs)
        stats = eng.pool.stats()  # asserts the accounting invariants
        assert stats["sequences"] == 0
        assert stats["allocs"] - stats["frees"] == 0


class TestRouter:
    def test_affinity_pressure_and_load_placement(self, model):
        clock = VirtualClock()
        engines = [make_engine(model, clock, num_slots=2,
                               prefix_sharing=True) for _ in range(2)]
        router = FleetRouter(engines)
        h1 = router.submit(list(TEMPLATE) + [40], 4)
        router.run_until_idle()
        h2 = router.submit(list(TEMPLATE) + [41], 4)  # trie match -> r0
        h3 = router.submit([9, 8, 7], 4)  # no affinity; r0 busier -> r1
        router.run_until_idle()
        assert [p["replica"] for p in router.placements] == [0, 0, 1]
        assert [p["reason"] for p in router.placements] == \
            ["pressure", "affinity", "pressure"]
        assert all(h.status == "completed" for h in (h1, h2, h3))

    def test_bounded_retries_on_shed(self, model):
        clock = VirtualClock()
        engines = [make_engine(model, clock, num_slots=2,
                               prefix_sharing=True) for _ in range(2)]
        router = FleetRouter(engines)
        router.submit(list(TEMPLATE) + [40], 4)
        router.run_until_idle()
        engines[0].batcher.set_shed("controller shed: sustained SLO burn")
        h = router.submit(list(TEMPLATE) + [41], 4)  # affinity r0 -> shed
        router.run_until_idle()
        assert h.status == "completed"
        assert router.placements[-1] == {"request_id": h.request_id,
                                         "replica": 1, "reason": "retry"}
        engines[1].batcher.set_shed("controller shed: sustained SLO burn")
        h2 = router.submit(list(TEMPLATE) + [42], 4)  # everyone sheds
        assert h2.status == "rejected" and h2.shed_reason == "controller"
        # validation rejections do NOT re-route (identical everywhere)
        n_place = len(router.placements)
        engines[0].batcher.clear_shed()
        engines[1].batcher.clear_shed()
        bad = router.submit([], 4)
        assert bad.status == "rejected" and bad.shed_reason is None
        assert len(router.placements) == n_place

    def test_fleet_replay_is_bitwise(self, model, draft):
        trace = generate_shared_prefix_load(
            23, 14, vocab=CFG.vocab_size, n_templates=2, prefix_len=16,
            suffix_len=(2, 6), max_new=(2, 6), shared_fraction=0.7,
            unique_len=(4, 12), mean_gap_s=0.004)

        def run():
            # the storm detector is process-global with a real-time
            # window; 2 engines x 5 jit sites per run cross its default
            # threshold at a wall-clock-dependent point — reset per run
            # (the conftest does the same per test)
            from hetu_tpu.obs import compile as obs_compile
            obs_compile.configure_storm(None)
            clock = VirtualClock()
            engines = [make_engine(model, clock, num_slots=2,
                                   sampling="top_k", top_k=5,
                                   prefix_sharing=True, draft_model=draft,
                                   spec_k=2) for _ in range(2)]
            router = FleetRouter(engines)
            jr = obs_journal.EventJournal(clock=clock)
            handles, i = [], 0
            with obs_journal.use(jr):
                while i < len(trace) or not router.idle:
                    while i < len(trace) and \
                            trace[i].submit_at <= clock.t:
                        it = trace[i]
                        handles.append(router.submit(
                            list(it.prompt), it.max_new_tokens))
                        i += 1
                    router.step()
                    clock.advance(0.001)
            streams = [(h.status, tuple(h.tokens), h.stream_fingerprint)
                       for h in handles]
            # compile events carry measured wall time (duration_s) —
            # normalize it out, the gang norm_events convention; every
            # other field (virtual ts and seq included) must be bitwise
            events = [{k: v for k, v in e.items() if k != "duration_s"}
                      for e in jr.events]
            return router.placements, streams, events

        p1, s1, j1 = run()
        p2, s2, j2 = run()
        assert p1 == p2          # identical placement sequence
        assert s1 == s2          # identical streams + fingerprints
        assert j1 == j2          # identical journal, seq/ts included
        assert any(e["kind"] == "prefix_share" for e in j1)
        assert any(e["kind"] == "router_place" for e in j1)

    def test_fleet_endpoint_smoke(self, model):
        import time as _time
        engines = [ServingEngine(model, num_slots=2, page_size=8,
                                 max_seq_len=64, prompt_buckets=(8, 16, 32),
                                 seed=11, sampling="greedy",
                                 prefix_sharing=True,
                                 clock=_time.monotonic) for _ in range(2)]
        router = FleetRouter(engines)
        srv = serve_fleet_router(router, port=0)
        try:
            url = f"http://127.0.0.1:{srv.port}"

            def post(payload):
                req = urllib.request.Request(
                    f"{url}/infer", data=json.dumps(payload).encode(),
                    method="POST")
                with urllib.request.urlopen(req, timeout=30) as r:
                    return json.loads(r.read())

            # one shared-prefix pair through the fleet front end
            r1 = post({"prompt": list(TEMPLATE) + [40],
                       "max_new_tokens": 4})
            r2 = post({"prompt": list(TEMPLATE) + [41],
                       "max_new_tokens": 4})
            assert r1["status"] == r2["status"] == "completed"
            assert len(r1["tokens"]) == 4
            with urllib.request.urlopen(f"{url}/fleet/serve",
                                        timeout=30) as r:
                stats = json.loads(r.read())
            assert stats["num_replicas"] == 2
            assert len(stats["replicas"]) == 2
            assert sum(stats["placements_by_reason"].values()) == 2
            assert stats["placements_by_reason"].get("affinity", 0) >= 1
        finally:
            srv.stop()
            router.stop()


class TestSharedPrefixLoadgen:
    def test_trace_is_deterministic(self):
        kw = dict(vocab=97, n_templates=3, prefix_len=8,
                  shared_fraction=0.6)
        a = generate_shared_prefix_load(5, 40, **kw)
        b = generate_shared_prefix_load(5, 40, **kw)
        assert a == b
        assert a != generate_shared_prefix_load(6, 40, **kw)

    def test_template_mixture(self):
        trace = generate_shared_prefix_load(
            9, 200, vocab=97, n_templates=3, prefix_len=8,
            suffix_len=(2, 4), shared_fraction=0.7, unique_len=(3, 9))
        shared = [it for it in trace if it.template is not None]
        unique = [it for it in trace if it.template is None]
        assert shared and unique
        assert abs(len(shared) / len(trace) - 0.7) < 0.1
        # all shared items of one template carry the identical prefix
        by_tid: dict = {}
        for it in shared:
            by_tid.setdefault(it.template, set()).add(it.prompt[:8])
        assert all(len(prefixes) == 1 for prefixes in by_tid.values())
        assert set(by_tid) == {0, 1, 2}
        for it in unique:
            assert 3 <= len(it.prompt) <= 9


@pytest.mark.slow
class TestFleetAcceptance:
    def test_fleet_beats_single_replica(self, model, draft):
        """The tentpole's measured win: 2 replicas + prefix sharing +
        speculation vs one bare replica on the same template-heavy
        trace — decode tokens/s and TTFT p99 from the SLO histograms.

        Measured in VIRTUAL time: one fleet tick steps every replica and
        advances the shared clock once — the N-chips deployment model,
        where replicas run in parallel.  (In this process the replicas
        necessarily timeshare one device, so wall clock would measure
        the simulation harness, not the fleet; ``bench.py --mode serve
        --replicas N`` owns the on-chip wall-clock numbers.)  The SLO
        histograms are driven by the same injected clock, so TTFT p99 is
        the queueing-delay improvement of 2x admission capacity, and
        tokens/s(virtual) captures speculation's k+1-tokens-per-tick and
        sharing's suffix-only prefill."""
        trace = generate_shared_prefix_load(
            31, 20, vocab=CFG.vocab_size, n_templates=2, prefix_len=16,
            suffix_len=(2, 6), max_new=(8, 12), shared_fraction=0.8,
            unique_len=(4, 12), mean_gap_s=0.001)
        reg = obs_registry.get_registry()
        hist = reg.histogram("hetu_serve_ttft_seconds").labels()

        def run(n, **kw):
            clock = VirtualClock()
            engines = [make_engine(model, clock, num_slots=2,
                                   queue_depth=len(trace) + 1, **kw)
                       for _ in range(n)]
            router = FleetRouter(engines)
            cum0 = hist.cumulative()
            kvmod.reset_pages_written_count()
            handles, i, t0 = [], 0, clock.t
            while i < len(trace) or not router.idle:
                while i < len(trace) and trace[i].submit_at <= clock.t:
                    it = trace[i]
                    handles.append(router.submit(list(it.prompt),
                                                 it.max_new_tokens))
                    i += 1
                router.step()
                clock.advance(0.001)
            dt = clock.t - t0
            done = [h for h in handles if h.status == "completed"]
            assert len(done) == len(trace)
            tokens = sum(max(len(h.tokens) - 1, 0) for h in done)
            from hetu_tpu.obs.registry import Histogram
            p99 = Histogram.quantile_from_cumulative(
                cum0, hist.cumulative(), 0.99)
            return tokens / dt, p99, kvmod.pages_written_count()

        fleet_tps, fleet_p99, fleet_pages = run(
            2, prefix_sharing=True, draft_model=model, spec_k=3)
        single_tps, single_p99, single_pages = run(1)
        assert fleet_tps > single_tps, (fleet_tps, single_tps)
        assert fleet_p99 < single_p99, (fleet_p99, single_p99)
        # sharing's storage win rides along: fewer prefill pages written
        assert fleet_pages < single_pages, (fleet_pages, single_pages)

    def test_multi_replica_shed_freeze_chaos_replays(self, model):
        """3 replicas under mid-trace shed latches + a bucket freeze:
        every request resolves, re-routes are bounded, and the whole run
        (placements, streams, outcomes) replays bitwise."""
        trace = generate_shared_prefix_load(
            41, 18, vocab=CFG.vocab_size, n_templates=3, prefix_len=16,
            suffix_len=(2, 6), max_new=(2, 5), shared_fraction=0.6,
            unique_len=(4, 12), mean_gap_s=0.003)

        def run():
            clock = VirtualClock()
            engines = [make_engine(model, clock, num_slots=2,
                                   prefix_sharing=True)
                       for _ in range(3)]
            router = FleetRouter(engines)
            handles, i, tick = [], 0, 0
            while i < len(trace) or not router.idle:
                tick += 1
                if tick == 3:
                    engines[0].batcher.set_shed("controller shed: chaos")
                if tick == 6:
                    engines[0].batcher.clear_shed()
                    engines[1].freeze_bucket_growth = True
                if tick == 10:
                    engines[1].freeze_bucket_growth = False
                while i < len(trace) and trace[i].submit_at <= clock.t:
                    it = trace[i]
                    handles.append(router.submit(list(it.prompt),
                                                 it.max_new_tokens))
                    i += 1
                router.step()
                clock.advance(0.001)
            assert all(h.done for h in handles)
            return (router.placements,
                    [(h.status, tuple(h.tokens)) for h in handles])

        assert run() == run()
