"""Sharded host-embedding tests: key routing, server-side SGD math,
trainer-protocol integration, persistence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_tpu.core import set_random_seed
from hetu_tpu.embed.sharded import ShardedHostEmbedding
from hetu_tpu.exec import Trainer
from hetu_tpu.optim import AdamOptimizer


def test_routing_covers_all_ids():
    set_random_seed(0)
    emb = ShardedHostEmbedding(100, 4, n_shards=3)
    ids = np.arange(100, dtype=np.int64)
    shard, local = emb._route(ids)
    # bijective: (shard, local) pairs are unique and local within range
    assert len({(s, l) for s, l in zip(shard, local)}) == 100
    assert local.max() < -(-100 // 3)


def test_push_applies_sgd_per_shard():
    set_random_seed(0)
    lr = 0.1
    emb = ShardedHostEmbedding(64, 8, n_shards=4, optimizer="sgd", lr=lr)
    ids = np.asarray([0, 1, 5, 17, 33, 63], np.int64)
    before = emb.pull_rows(ids).copy()
    emb.stage(jnp.asarray(ids))
    g = np.random.default_rng(0).normal(size=(len(ids), 8)).astype(np.float32)
    emb.push_grads(g)
    after = emb.pull_rows(ids)
    np.testing.assert_allclose(after, before - lr * g, rtol=1e-5, atol=1e-6)


def test_duplicate_ids_accumulate():
    set_random_seed(0)
    lr = 1.0
    emb = ShardedHostEmbedding(10, 4, n_shards=2, optimizer="sgd", lr=lr)
    ids = np.asarray([3, 3, 3], np.int64)
    before = emb.pull_rows([3]).copy()
    emb.stage(jnp.asarray(ids))
    g = np.ones((3, 4), np.float32)
    emb.push_grads(g)
    after = emb.pull_rows([3])
    # engine semantics: duplicate rows in one push accumulate
    np.testing.assert_allclose(after, before - lr * 3 * g[:1], rtol=1e-5)


def test_trainer_integration_and_convergence():
    set_random_seed(0)
    from hetu_tpu.core.module import Module
    from hetu_tpu.layers import Linear

    class Tiny(Module):
        def __init__(self):
            self.emb = ShardedHostEmbedding(200, 8, n_shards=4,
                                            optimizer="adagrad", lr=0.2,
                                            cache_capacity=200)
            self.head = Linear(8, 1)

        def loss(self, ids, y):
            h = self.emb(ids).mean(axis=1)
            pred = self.head(h)[:, 0]
            return jnp.mean((pred - y) ** 2), {}

    rng = np.random.default_rng(0)
    model = Tiny()
    trainer = Trainer(model, AdamOptimizer(3e-3),
                      lambda m, b, k: m.loss(b["ids"], b["y"]))
    losses = []
    for _ in range(40):
        ids = rng.integers(0, 200, (64, 5))
        y = (ids[:, 0] % 2).astype(np.float32)
        b = {"ids": jnp.asarray(ids, jnp.int32), "y": jnp.asarray(y)}
        for m_ in trainer.staged_modules():
            m_.stage(b["ids"])
        losses.append(float(trainer.step(b)["loss"]))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_save_load_roundtrip(tmp_path):
    set_random_seed(0)
    emb = ShardedHostEmbedding(50, 4, n_shards=3)
    ids = np.arange(50, dtype=np.int64)
    rows = emb.pull_rows(ids).copy()
    emb.save(str(tmp_path / "emb"))
    set_random_seed(1)
    emb2 = ShardedHostEmbedding(50, 4, n_shards=3, seed=99)
    assert not np.allclose(emb2.pull_rows(ids), rows)
    emb2.load(str(tmp_path / "emb"))
    np.testing.assert_allclose(emb2.pull_rows(ids), rows, rtol=1e-6)


def test_push_before_stage_raises():
    set_random_seed(0)
    emb = ShardedHostEmbedding(10, 4, n_shards=2)
    with pytest.raises(RuntimeError):
        emb.push_grads(np.zeros((2, 4), np.float32))


def test_shard_loads_tracking():
    set_random_seed(0)
    emb = ShardedHostEmbedding(40, 4, n_shards=4, optimizer="sgd", lr=0.1)
    ids = np.asarray([0, 1, 2, 3, 4, 5, 6, 7], np.int64)  # 2 rows per shard
    emb.stage(jnp.asarray(ids))
    emb.push_grads(np.zeros((8, 4), np.float32))
    loads = emb.loads()
    np.testing.assert_array_equal(loads["pull_rows"], [2, 2, 2, 2])
    np.testing.assert_array_equal(loads["push_rows"], [2, 2, 2, 2])
