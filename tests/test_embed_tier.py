"""Tiered embedding fabric: HBM -> host -> PS tiering + int8 PS storage.

The A/B acceptance bar is EXACT accounting: the per-tier hit counters
must match what an independent replay of the id trace computes (no
vibes), the int8 tier must hit its byte-reduction floor with the quality
delta bounded, and strict-freshness tiering must train bit-compatibly
with the plain staged path (the tier is a transport optimization, not a
semantics change).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from hetu_tpu.core import set_random_seed
from hetu_tpu.core.module import Module
from hetu_tpu.embed import (HostEmbeddingTable, Int8HostEmbeddingTable,
                            StagedHostEmbedding, TieredEmbedding,
                            TierPolicy)
from hetu_tpu.embed.compress.quant import dequantize_rows, quantize_rows
from hetu_tpu.exec import Trainer
from hetu_tpu.layers import Linear
from hetu_tpu.obs import journal as obs_journal
from hetu_tpu.ops import binary_cross_entropy_with_logits
from hetu_tpu.optim import AdamOptimizer

pytestmark = pytest.mark.embed_tier


# ------------------------------------------------------------ tier policy

class Tiny(Module):
    def __init__(self, emb):
        self.emb = emb
        self.head = Linear(4 * 3, 1)

    def loss(self, sp, y):
        e = self.emb(sp).reshape(sp.shape[0], -1)
        return binary_cross_entropy_with_logits(self.head(e)[:, 0], y).mean()


def _train(emb, steps=12, batch=16):
    set_random_seed(0)
    model = Tiny(emb)
    tr = Trainer(model, AdamOptimizer(1e-2),
                 lambda m, b, k: (m.loss(b["sp"], b["y"]), {}))
    rng = np.random.default_rng(0)
    sp = np.minimum(rng.zipf(1.5, (64, 3)) - 1, 49).astype(np.int32)
    y = (sp.sum(1) % 2).astype(np.float32)
    losses = []
    for s in range(steps):
        lo = (s * batch) % (len(y) - batch)
        b = {"sp": jnp.asarray(sp[lo:lo + batch]),
             "y": jnp.asarray(y[lo:lo + batch])}
        for m in tr.staged_modules():
            m.stage(b["sp"])
        losses.append(float(tr.step(b)["loss"]))
    return losses, tr


def test_promote_demote_smoke():
    """Tier-1 smoke: a row earns HBM residency on its promote_touches-th
    batch, idles out after demote_idle stages, and both transitions are
    journaled."""
    j = obs_journal.EventJournal()
    with obs_journal.use(j):
        emb = TieredEmbedding(100, 8, hbm_capacity=8, host_capacity=32,
                              policy=TierPolicy(promote_touches=2,
                                                demote_idle=3),
                              optimizer="sgd", lr=1.0, name="smoke")
        ids = jnp.asarray([[1, 2, 3]])
        emb.stage(ids)                      # touch 1: host-served
        v1 = np.asarray(emb(ids)).copy()
        assert emb.tier_stats()["hbm"]["resident"] == 0
        emb._handle.ids = None
        emb.stage(ids)                      # touch 2: promoted
        v2 = np.asarray(emb(ids))
        st = emb.tier_stats()
        assert st["hbm"]["resident"] == 3 and st["hbm"]["promotions"] == 3
        np.testing.assert_allclose(v1, v2, rtol=1e-6)
        np.testing.assert_allclose(
            v1[0], emb.table.pull(np.array([1, 2, 3])), rtol=1e-6)
        for k in range(4):                  # idle the hot rows out
            emb._handle.ids = None
            emb.stage(jnp.asarray([[10 + k]]))
        st = emb.tier_stats()
        assert st["hbm"]["demotions"] == 3
        assert not any(emb._handle.slot_of[[1, 2, 3]] >= 0)
    kinds = [e["kind"] for e in j.events]
    assert "tier_promote" in kinds and "tier_demote" in kinds


def test_tiered_strict_matches_staged_oracle():
    """Strict freshness + always-promote == the plain staged path, step
    by step and in the final host table — tiering is a transport
    optimization, not a semantics change."""
    set_random_seed(0)
    l_ref, tr_ref = _train(StagedHostEmbedding(50, 4, optimizer="adagrad",
                                               lr=0.05, seed=7))
    set_random_seed(0)
    l_tier, tr_tier = _train(TieredEmbedding(
        50, 4, hbm_capacity=64, host_capacity=128,
        policy=TierPolicy(promote_touches=1), hbm_pull_bound=0,
        optimizer="adagrad", lr=0.05, seed=7))
    np.testing.assert_allclose(l_tier, l_ref, rtol=1e-5)
    ids = np.arange(50)
    np.testing.assert_allclose(tr_tier.state.model.emb.table.pull(ids),
                               tr_ref.state.model.emb.table.pull(ids),
                               rtol=1e-5)


def test_tiered_cold_path_matches_staged_oracle():
    """Same bit-compatibility with the promotion gate ON (cold rows ride
    the host path for their first touches) — value routing never changes
    the math."""
    set_random_seed(0)
    l_ref, tr_ref = _train(StagedHostEmbedding(50, 4, optimizer="adagrad",
                                               lr=0.05, seed=7))
    set_random_seed(0)
    l_tier, tr_tier = _train(TieredEmbedding(
        50, 4, hbm_capacity=16, host_capacity=64,
        policy=TierPolicy(promote_touches=3), hbm_pull_bound=0,
        optimizer="adagrad", lr=0.05, seed=7))
    np.testing.assert_allclose(l_tier, l_ref, rtol=1e-5)
    ids = np.arange(50)
    np.testing.assert_allclose(tr_tier.state.model.emb.table.pull(ids),
                               tr_ref.state.model.emb.table.pull(ids),
                               rtol=1e-5)


def _counter_oracle(trace, *, promote_touches, pull_bound, train):
    """Independent replay of the documented tier policy over an id trace
    (no-eviction regime: capacity >= distinct rows).  Returns the
    expected HBM counters."""
    touches, staleness, resident = {}, {}, set()
    hits = misses = promotions = 0
    for batch in trace:
        uniq = sorted(set(int(i) for i in batch.ravel()))
        for r in uniq:
            touches[r] = touches.get(r, 0) + 1
        for r in uniq:
            if r in resident:
                if staleness.get(r, 0) > pull_bound:
                    misses += 1     # stale: re-pull, stays resident
                    staleness[r] = 0
                else:
                    hits += 1
            elif touches[r] >= promote_touches:
                misses += 1
                promotions += 1
                resident.add(r)
                staleness[r] = 0
            else:
                misses += 1         # cold: host-served, not promoted
        if train:
            for r in uniq:          # push bumps every touched row
                staleness[r] = staleness.get(r, 0) + 1
    return {"hits": hits, "misses": misses, "promotions": promotions}


@pytest.mark.parametrize("train,pull_bound", [(False, 0), (True, 0),
                                              (True, 2)])
def test_counters_match_trace_reuse_exactly(train, pull_bound):
    """The acceptance bar: per-tier hit counters == the trace's computed
    reuse, exactly — including the cross-tier invariant that every HBM
    miss is one host-tier row (host hits + host misses == hbm misses)."""
    rng = np.random.default_rng(5)
    trace = [np.minimum(rng.zipf(1.4, (6, 3)) - 1, 79).astype(np.int64)
             for _ in range(20)]
    emb = TieredEmbedding(80, 4, hbm_capacity=96, host_capacity=256,
                          policy=TierPolicy(promote_touches=2),
                          hbm_pull_bound=pull_bound, host_pull_bound=0,
                          optimizer="sgd", lr=1.0, name=f"ex{train}"
                                                       f"{pull_bound}")
    for batch in trace:
        emb.stage(batch)
        if train:
            emb.push_grads(np.ones(batch.shape + (4,), np.float32))
        else:
            emb._handle.ids = None
    st = emb.tier_stats()
    want = _counter_oracle(trace, promote_touches=2, pull_bound=pull_bound,
                           train=train)
    assert st["hbm"]["hits"] == want["hits"]
    assert st["hbm"]["misses"] == want["misses"]
    assert st["hbm"]["promotions"] == want["promotions"]
    assert st["hbm"]["evictions"] == 0          # no-eviction regime
    host_total = st["host"]["hits"] + st["host"]["misses"]
    assert host_total == st["hbm"]["misses"]
    assert st["ps"]["rows_pulled"] == st["host"]["misses"]


def test_eviction_pressure_keeps_invariants():
    """Small HBM budget under a wide trace: residency stays bounded, the
    directory stays consistent, and hits+misses still covers every
    unique row staged."""
    rng = np.random.default_rng(7)
    emb = TieredEmbedding(200, 4, hbm_capacity=8, host_capacity=64,
                          policy=TierPolicy(promote_touches=1),
                          optimizer="sgd", lr=1.0)
    total_uniq = 0
    for _ in range(30):
        batch = rng.integers(0, 200, (4, 3))
        total_uniq += len(set(int(i) for i in batch.ravel()))
        emb.stage(batch)
        emb._handle.ids = None
    h = emb._handle
    st = emb.tier_stats()
    assert st["hbm"]["resident"] <= 8
    assert st["hbm"]["hits"] + st["hbm"]["misses"] == total_uniq
    for s in range(8):          # directory round-trips
        if h.id_of[s] >= 0:
            assert h.slot_of[h.id_of[s]] == s


def test_tier_metrics_published():
    from hetu_tpu.obs import registry as obs_registry

    emb = TieredEmbedding(50, 4, hbm_capacity=8, host_capacity=32,
                          name="pubsmoke")
    emb.stage(jnp.asarray([[1, 2]]))
    emb._handle.ids = None
    emb.stage(jnp.asarray([[1, 2]]))
    snap = obs_registry.get_registry().snapshot()
    for fam in ("hetu_embed_hits_total", "hetu_embed_misses_total",
                "hetu_embed_promotions_total", "hetu_embed_evictions_total",
                "hetu_embed_pull_bytes_total"):
        keys = [k for k in snap if k.startswith(fam)
                and "pubsmoke" in k and "tier=" in k.replace('"', "")]
        assert keys, f"{fam} not published: {sorted(snap)[:5]}"


def test_seed_hot_rows_promotes_on_first_touch():
    from hetu_tpu.embed.net import hot_row_signal

    emb = TieredEmbedding(50, 4, hbm_capacity=8, host_capacity=32,
                          policy=TierPolicy(promote_touches=3))
    emb.seed_hot_rows(hot_row_signal({"hot_rows": [(7, 99), (9, 50)]}))
    emb.stage(jnp.asarray([[7, 9, 11]]))
    h = emb._handle
    assert h.slot_of[7] >= 0 and h.slot_of[9] >= 0  # seeded: first touch
    assert h.slot_of[11] < 0                        # unseeded: still cold


# ------------------------------------------------------------ int8 storage

def test_quant_roundtrip_property():
    """Seeded property: per-row quantization reconstructs within half a
    code step per element (the documented tolerance), rows of any scale,
    including constant rows."""
    rng = np.random.default_rng(11)
    for _ in range(20):
        scale = 10.0 ** rng.uniform(-4, 2)
        rows = (rng.normal(size=(16, 32)) * scale).astype(np.float32)
        rows[3] = 0.0                       # constant row edge case
        rows[4] = 7.5
        q, s, m = quantize_rows(rows)
        back = dequantize_rows(q, s, m)
        tol = np.maximum(s[:, None] * 0.5, 1e-7)  # half a code step
        assert np.all(np.abs(back - rows) <= tol + 1e-6 * np.abs(rows))


def test_int8_store_pull_push_pull_matches_f32():
    """int8 store -> pull -> push -> pull tracks the f32 table within the
    documented tolerance: touched rows ride the float shadow (exact
    optimizer math), so the residual error is bounded by the INITIAL
    quantization step, never compounded by training."""
    f32 = HostEmbeddingTable(200, 32, seed=3, optimizer="adagrad", lr=0.05,
                             init_scale=0.05)
    i8 = HostEmbeddingTable(200, 32, seed=3, optimizer="adagrad", lr=0.05,
                            init_scale=0.05, storage="int8")
    assert isinstance(i8, Int8HostEmbeddingTable)
    ids = np.arange(200)
    step = float(i8._scale.astype(np.float32).max())
    np.testing.assert_allclose(i8.pull(ids), f32.pull(ids),
                               atol=step, rtol=0)
    rng = np.random.default_rng(0)
    keys = np.arange(20)
    for _ in range(10):
        g = rng.normal(size=(20, 32)).astype(np.float32)
        f32.push(keys, g)
        i8.push(keys, g)
    # trajectories differ only through the quantized INITIAL values
    np.testing.assert_allclose(i8.pull(keys), f32.pull(keys),
                               atol=5 * step, rtol=0)
    # untouched rows: still within one quantization step of f32
    cold = np.arange(100, 200)
    np.testing.assert_allclose(i8.pull(cold), f32.pull(cold),
                               atol=step, rtol=0)


def test_int8_resident_and_wire_bytes_floor():
    """Acceptance: resident + wire bytes reduced >= 3.5x (dim 64, the
    documented configuration; per-row f16 scale/middle overhead)."""
    f32 = HostEmbeddingTable(2000, 64, seed=0)
    i8 = HostEmbeddingTable(2000, 64, seed=0, storage="int8",
                            shadow_limit=20)
    # train a hot subset so the shadow is realistically non-empty
    for _ in range(5):
        i8.push(np.arange(20), np.ones((20, 64), np.float32))
    assert len(i8._shadow) <= 20
    resident_ratio = f32.resident_bytes() / i8.resident_bytes()
    wire_ratio = f32.pull_wire_bytes(1000) / i8.pull_wire_bytes(1000)
    assert resident_ratio >= 3.5, resident_ratio
    assert wire_ratio >= 3.5, wire_ratio


def test_int8_wdl_ctr_quality_delta_bounded():
    """Acceptance: wdl_ctr trained on int8 PS storage stays within the
    documented tolerance of f32 — loss trajectory within 2e-2 absolute,
    ranking (AUC) within 0.02."""
    from hetu_tpu.models import CTRConfig, WideDeep

    def run(storage):
        set_random_seed(0)
        cfg = CTRConfig(vocab=300, embed_dim=16, embedding="host",
                        host_bridge="staged", cache_capacity=0,
                        host_optimizer="adagrad", host_lr=0.05,
                        storage=storage)
        model = WideDeep(cfg)
        tr = Trainer(model, AdamOptimizer(1e-3),
                     lambda m, b, k: m.loss(b["dense"], b["sparse"],
                                            b["label"]))
        rng = np.random.default_rng(0)
        b = {"dense": jnp.asarray(rng.normal(size=(32, 13)), jnp.float32),
             "sparse": jnp.asarray(rng.integers(0, 300, (32, 26)),
                                   jnp.int32),
             "label": jnp.asarray(rng.integers(0, 2, (32,)), jnp.float32)}
        losses = []
        for _ in range(15):
            for m in tr.staged_modules():
                m.stage(b["sparse"])
            out = tr.step(b)
            losses.append(float(out["loss"]))
        pred = np.asarray(out["pred"])
        return np.asarray(losses), pred, np.asarray(b["label"])

    l_f32, p_f32, y = run("f32")
    l_i8, p_i8, _ = run("int8")
    assert l_f32[-1] < l_f32[0] and l_i8[-1] < l_i8[0]
    np.testing.assert_allclose(l_i8, l_f32, atol=2e-2, rtol=0)

    def auc(pred, y):
        order = np.argsort(pred, kind="stable")
        rank = np.empty_like(order, float)
        rank[order] = np.arange(1, len(pred) + 1)
        pos = y > 0.5
        n1, n0 = int(pos.sum()), int((~pos).sum())
        return ((rank[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)
                if n1 and n0 else 0.5)

    assert abs(auc(p_i8, y) - auc(p_f32, y)) < 0.02


def test_int8_cached_layer_trains():
    """The full composition: int8 PS + PythonCacheTable host tier under a
    staged layer — trains, and the read-only guard still bites."""
    from hetu_tpu.embed import PythonCacheTable

    emb = StagedHostEmbedding(50, 4, optimizer="adagrad", lr=0.05, seed=7,
                              cache_capacity=32, storage="int8")
    assert isinstance(emb.store, PythonCacheTable)
    losses, _ = _train(emb)
    assert losses[-1] < losses[0]
    emb.store.read_only = True
    with pytest.raises(RuntimeError, match="read-only"):
        emb.store.push([1], np.zeros((1, 4), np.float32))


def test_ctr_config_tiered_path():
    from hetu_tpu.models import CTRConfig, WideDeep

    set_random_seed(0)
    cfg = CTRConfig(vocab=200, embed_dim=4, embedding="tiered",
                    cache_capacity=64, host_cache_capacity=256,
                    host_optimizer="adagrad", host_lr=0.05,
                    promote_touches=2)
    model = WideDeep(cfg)
    tr = Trainer(model, AdamOptimizer(1e-3),
                 lambda m, b, k: m.loss(b["dense"], b["sparse"], b["label"]))
    rng = np.random.default_rng(0)
    b = {"dense": jnp.asarray(rng.normal(size=(16, 13)), jnp.float32),
         "sparse": jnp.asarray(rng.integers(0, 200, (16, 26)), jnp.int32),
         "label": jnp.asarray(rng.integers(0, 2, (16,)), jnp.float32)}
    for m in tr.staged_modules():
        m.stage(b["sparse"])
    l0 = float(tr.step(b)["loss"])
    for _ in range(10):
        for m in tr.staged_modules():
            m.stage(b["sparse"])
        out = tr.step(b)
    assert float(out["loss"]) < l0
    st = model.embed.tier_stats()
    assert st["hbm"]["promotions"] > 0      # the hot set landed in HBM


# --------------------------------------------------------- calibration

def test_calibration_ingest_embed_and_sentinel():
    """ingest_embed records the tier profile; a degraded later version
    (hit rate down >10%) trips the PR 12 regression sentinel naming the
    metric."""
    from hetu_tpu.obs.calibration import ProfileStore

    store = ProfileStore(clock=lambda: 0.0)
    good = {"hbm": {"hit_rate": 0.8, "resident": 10, "promotions": 5,
                    "demotions": 0, "evictions": 0},
            "host": {"hit_rate": 0.9},
            "ps": {"resident_bytes": 1000},
            "pull_bytes_per_stage": 100.0, "stages": 10}
    rec = store.ingest_embed(good, model_sig="wdl_ctr", device_kind="cpu")
    assert rec["version"] == 1
    bad = {**good, "hbm": {**good["hbm"], "hit_rate": 0.5}}
    j = obs_journal.EventJournal()
    with obs_journal.use(j):
        store.ingest_embed(bad, model_sig="wdl_ctr", device_kind="cpu")
    regs = [e for e in j.events if e["kind"] == "perf_regression"]
    assert regs and regs[0]["metric"] == "hbm_hit_rate"
