"""PipeDream 1F1B schedule tests on the virtual 8-device CPU mesh.

Oracles (the validate_results.py discipline):
- synchronous 1F1B gradients == jax.grad of the sequential stack (exact);
- async PipeDream with a single stage == a sequential per-microbatch SGD
  loop (exact — no staleness is possible at S=1);
- async PipeDream at S=4: same-direction convergence on a toy regression,
  and zero-lr invariance (weight stashing must keep params bit-identical).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from hetu_tpu.optim import SGDOptimizer
from hetu_tpu.parallel.mesh import MeshSpec, make_mesh
from hetu_tpu.parallel.pipedream import pipedream_grads, pipedream_train_step


def make_params(rng, S, d):
    # one linear weight + bias per stage
    return {
        "w": jnp.asarray(rng.normal(0, 0.5, (S, d, d)), jnp.float32),
        "b": jnp.asarray(rng.normal(0, 0.1, (S, d)), jnp.float32),
    }


def stage_fn(W, h, ex):
    return jnp.tanh(h @ W["w"] + W["b"])


def loss_fn(out, y):
    return jnp.mean((out - y) ** 2)


def seq_forward(params, x):
    h = x
    for s in range(params["w"].shape[0]):
        h = stage_fn({"w": params["w"][s], "b": params["b"][s]}, h, None)
    return h


@pytest.fixture
def pp4_mesh():
    return make_mesh(MeshSpec(pp=4, dp=2), devices=jax.devices())


# slow tier (r5 budget, 1-core box): the dp-axis variant and the interleaved V tests keep the sync schedule gated fast
@pytest.mark.slow
def test_sync_1f1b_grads_match_sequential(pp4_mesh):
    rng = np.random.default_rng(0)
    S, d, B, M = 4, 8, 16, 8
    params = make_params(rng, S, d)
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)

    def ref_loss(p):
        # mean over microbatches of per-microbatch loss == global mean here
        xs = x.reshape(M, B // M, d)
        ys = y.reshape(M, B // M, d)
        return jnp.mean(jax.vmap(
            lambda xm, ym: loss_fn(seq_forward(p, xm), ym))(xs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)

    loss, grads = jax.jit(lambda p: pipedream_grads(
        stage_fn, loss_fn, p, x, y, mesh=pp4_mesh, n_microbatches=M,
    ))(params)

    np.testing.assert_allclose(loss, ref_l, rtol=1e-6)
    np.testing.assert_allclose(grads["w"], ref_g["w"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(grads["b"], ref_g["b"], rtol=1e-5, atol=1e-6)


def test_sync_1f1b_grads_with_dp_axis(pp4_mesh):
    rng = np.random.default_rng(1)
    S, d, B, M = 4, 8, 32, 4
    params = make_params(rng, S, d)
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)

    def ref_loss(p):
        xs = x.reshape(M, B // M, d)
        ys = y.reshape(M, B // M, d)
        return jnp.mean(jax.vmap(
            lambda xm, ym: loss_fn(seq_forward(p, xm), ym))(xs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = jax.jit(lambda p: pipedream_grads(
        stage_fn, loss_fn, p, x, y, mesh=pp4_mesh, n_microbatches=M,
        dp_axis="dp",
    ))(params)
    np.testing.assert_allclose(loss, ref_l, rtol=1e-6)
    np.testing.assert_allclose(grads["w"], ref_g["w"], rtol=1e-5, atol=1e-6)


def test_async_single_stage_matches_sequential_sgd():
    mesh = make_mesh(MeshSpec(pp=1), devices=jax.devices()[:1])
    rng = np.random.default_rng(2)
    d, B, M = 8, 16, 8
    params = make_params(rng, 1, d)
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    lr = 0.05
    opt = SGDOptimizer(lr)
    state = opt.init(params)

    loss, newp, newst = jax.jit(lambda p, s: pipedream_train_step(
        stage_fn, loss_fn, opt, p, s, x, y, mesh=mesh, n_microbatches=M,
    ))(params, state)

    # oracle: per-microbatch SGD, same order
    ref = jax.tree_util.tree_map(lambda v: v, params)
    xs = np.asarray(x).reshape(M, B // M, d)
    ys = np.asarray(y).reshape(M, B // M, d)
    for m in range(M):
        g = jax.grad(lambda p: loss_fn(seq_forward(p, jnp.asarray(xs[m])),
                                       jnp.asarray(ys[m])))(ref)
        ref = jax.tree_util.tree_map(lambda p, gg: p - lr * gg, ref, g)

    np.testing.assert_allclose(newp["w"], ref["w"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(newp["b"], ref["b"], rtol=1e-5, atol=1e-6)
    assert int(newst["step"]) == M


def test_async_zero_lr_keeps_weights(pp4_mesh):
    rng = np.random.default_rng(3)
    S, d, B, M = 4, 8, 16, 8
    params = make_params(rng, S, d)
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    opt = SGDOptimizer(0.0)
    state = opt.init(params)
    loss, newp, _ = jax.jit(lambda p, s: pipedream_train_step(
        stage_fn, loss_fn, opt, p, s, x, y, mesh=pp4_mesh, n_microbatches=M,
    ))(params, state)
    np.testing.assert_array_equal(newp["w"], params["w"])
    # with frozen weights the async schedule degenerates to sync: its loss
    # must equal the sequential mean loss
    xs = x.reshape(M, B // M, d)
    ys = y.reshape(M, B // M, d)
    ref = jnp.mean(jax.vmap(
        lambda xm, ym: loss_fn(seq_forward(params, xm), ym))(xs, ys))
    np.testing.assert_allclose(loss, ref, rtol=1e-6)


def test_async_pipedream_converges(pp4_mesh):
    rng = np.random.default_rng(4)
    S, d, B, M = 4, 8, 16, 8
    params = make_params(rng, S, d)
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)) * 0.1, jnp.float32)
    opt = SGDOptimizer(0.05)
    state = opt.init(params)

    step = jax.jit(lambda p, s: pipedream_train_step(
        stage_fn, loss_fn, opt, p, s, x, y, mesh=pp4_mesh, n_microbatches=M))
    losses = []
    for _ in range(20):
        loss, params, state = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def test_async_hetpipe_dp_sync(pp4_mesh):
    """HetPipe: dp replicas see different data but pmean grads -> replicas
    stay consistent and loss converges."""
    rng = np.random.default_rng(5)
    S, d, B, M = 4, 8, 32, 4
    params = make_params(rng, S, d)
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)) * 0.1, jnp.float32)
    opt = SGDOptimizer(0.05)
    state = opt.init(params)
    step = jax.jit(lambda p, s: pipedream_train_step(
        stage_fn, loss_fn, opt, p, s, x, y, mesh=pp4_mesh, n_microbatches=M,
        dp_axis="dp"))
    losses = []
    for _ in range(20):
        loss, params, state = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


@pytest.mark.parametrize("V,M", [
    (2, 8), (2, 6),
    # slow tier (r5 re-tier pass 2): V=3 is the odd-chunk generality case
    pytest.param(3, 4, marks=pytest.mark.slow),
])
def test_interleaved_1f1b_grads_match_sequential(pp4_mesh, V, M):
    """Virtual-stage interleaving: grads of the depth-S*V stack with V
    chunks per device must equal jax.grad of the sequential stack (the
    (2,6) case has M % S != 0 — correct but extra bubble, per docs)."""
    from hetu_tpu.parallel.pipedream import (interleave_stages,
                                             uninterleave_stages)

    rng = np.random.default_rng(3)
    S, d, B = 4, 8, 24
    params = make_params(rng, S * V, d)  # depth order: u = v*S + d
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)

    def ref_loss(p):
        xs = x.reshape(M, B // M, d)
        ys = y.reshape(M, B // M, d)
        return jnp.mean(jax.vmap(
            lambda xm, ym: loss_fn(seq_forward(p, xm), ym))(xs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)

    loss, grads_dm = jax.jit(lambda p: pipedream_grads(
        stage_fn, loss_fn, interleave_stages(p, S, V), x, y,
        mesh=pp4_mesh, n_microbatches=M, virtual_stages=V,
    ))(params)
    grads = uninterleave_stages(grads_dm, S, V)

    np.testing.assert_allclose(loss, ref_l, rtol=1e-6)
    np.testing.assert_allclose(grads["w"], ref_g["w"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(grads["b"], ref_g["b"], rtol=1e-5, atol=1e-6)


def test_interleaved_with_dp_axis(pp4_mesh):
    """Interleaving composed with HetPipe-style dp gradient sync."""
    from hetu_tpu.parallel.pipedream import (interleave_stages,
                                             uninterleave_stages)

    rng = np.random.default_rng(4)
    S, V, d, B, M = 4, 2, 8, 16, 4
    params = make_params(rng, S * V, d)
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)

    def ref_loss(p):
        xs = x.reshape(M, B // M, d)
        ys = y.reshape(M, B // M, d)
        return jnp.mean(jax.vmap(
            lambda xm, ym: loss_fn(seq_forward(p, xm), ym))(xs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads_dm = jax.jit(lambda p: pipedream_grads(
        stage_fn, loss_fn, interleave_stages(p, S, V), x, y,
        mesh=pp4_mesh, n_microbatches=M, dp_axis="dp", virtual_stages=V,
    ))(params)
    grads = uninterleave_stages(grads_dm, S, V)
    np.testing.assert_allclose(loss, ref_l, rtol=1e-6)
    np.testing.assert_allclose(grads["w"], ref_g["w"], rtol=1e-5, atol=1e-6)


def test_interleaved_rejects_bad_leading_dim(pp4_mesh):
    rng = np.random.default_rng(5)
    params = make_params(rng, 4, 8)  # S*V would need 8
    x = jnp.zeros((8, 8), jnp.float32)
    with pytest.raises(ValueError, match="interleave_stages"):
        pipedream_grads(stage_fn, loss_fn, params, x, x, mesh=pp4_mesh,
                        n_microbatches=4, virtual_stages=2)


def test_schedule_stats_bubble_shrinks_with_V():
    from hetu_tpu.parallel.pipedream import pipedream_schedule_stats

    s1 = pipedream_schedule_stats(4, 1, 16)
    s2 = pipedream_schedule_stats(4, 2, 16)
    s4 = pipedream_schedule_stats(4, 4, 16)
    # classic 1F1B bubble at V=1: (S-1)/(M+S-1)
    assert abs(s1["bubble_fraction"] - 3 / 19) < 1e-9
    assert s4["bubble_fraction"] < s2["bubble_fraction"] < s1["bubble_fraction"]
    # the interleaved bound: bubble/ideal ~= (S-1)/(M*V)
    assert abs(s2["bubble_fraction"] - 3 / 35) < 1e-9


@pytest.mark.slow
def test_interleaved_1f1b_on_real_transformer_blocks(pp4_mesh):
    """The schedule on a REAL model, not a toy linear stage: 8
    TransformerBlocks stacked as the stage-params pytree (Modules ARE
    pytrees, so a virtual stage's slice is itself a callable block),
    embedding outside the ring, tied LM loss at the last virtual stage.
    Interleaved (pp=4, V=2) grads must match plain jax.grad backprop of
    the same depth-8 stack."""
    import jax.tree_util as jtu

    from hetu_tpu.core import set_random_seed
    from hetu_tpu.layers import Embedding, TransformerBlock
    from hetu_tpu.ops import softmax_cross_entropy_sparse
    from hetu_tpu.parallel.pipedream import (interleave_stages,
                                             uninterleave_stages)
    from hetu_tpu.parallel.pipeline import stack_modules

    S, V, d, H, vocab, B, M, L = 4, 2, 32, 4, 64, 8, 4, 8
    set_random_seed(11)
    blocks = [TransformerBlock(d, H, causal=True) for _ in range(L)]
    embed = Embedding(vocab, d)
    stacked = stack_modules(blocks)

    rng = np.random.default_rng(11)
    ids = jnp.asarray(rng.integers(0, vocab, (B, 12)), jnp.int32)
    x = embed(ids)
    head = embed.weight.T

    def stage_fn(blk, h, ex):
        return blk(h)

    def loss_fn(out, y):
        return softmax_cross_entropy_sparse(
            out[:, :-1] @ head, y[:, 1:]).mean()

    def ref_loss(stk):
        def apply_mb(xm, ym):
            h = xm
            for u in range(L):
                h = jtu.tree_map(lambda l: l[u], stk)(h)
            return loss_fn(h, ym)
        xs = x.reshape(M, B // M, *x.shape[1:])
        ys = ids.reshape(M, B // M, ids.shape[1])
        return jnp.mean(jax.vmap(apply_mb)(xs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(stacked)

    loss, grads_dm = jax.jit(lambda stk: pipedream_grads(
        stage_fn, loss_fn, interleave_stages(stk, S, V), x, ids,
        mesh=pp4_mesh, n_microbatches=M, virtual_stages=V))(stacked)
    grads = uninterleave_stages(grads_dm, S, V)

    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    for a, b in zip(jtu.tree_leaves(grads), jtu.tree_leaves(ref_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("V,M", [(2, 3), (2, 5), (4, 6), (3, 7)])
def test_interleaved_schedule_odd_combinations(pp4_mesh, V, M):
    """Awkward (V, M) combinations — M smaller than the group size, odd
    M, V not dividing M — must still be EXACT (the decode/validity
    masking guarantees correctness for any M; only bubble suffers)."""
    from hetu_tpu.parallel.pipedream import (interleave_stages,
                                             uninterleave_stages)

    rng = np.random.default_rng(V * 10 + M)
    S, d = 4, 8
    B = M * 2
    params = make_params(rng, S * V, d)
    x = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(B, d)), jnp.float32)

    def ref_loss(p):
        xs = x.reshape(M, B // M, d)
        ys = y.reshape(M, B // M, d)
        return jnp.mean(jax.vmap(
            lambda xm, ym: loss_fn(seq_forward(p, xm), ym))(xs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, g_dm = jax.jit(lambda p: pipedream_grads(
        stage_fn, loss_fn, interleave_stages(p, S, V), x, y,
        mesh=pp4_mesh, n_microbatches=M, virtual_stages=V))(params)
    grads = uninterleave_stages(g_dm, S, V)
    np.testing.assert_allclose(loss, ref_l, rtol=1e-6)
    np.testing.assert_allclose(grads["w"], ref_g["w"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(grads["b"], ref_g["b"], rtol=1e-5, atol=1e-6)
