"""Benchmark: BERT-large pretraining throughput + MFU on one chip.

The BASELINE headline metric (BASELINE.md): BERT-large pretraining
samples/sec/chip and model-FLOPs-utilization, bf16 compute.  Prints ONE JSON
line: {"metric", "value", "unit", "vs_baseline"} where value is MFU and
vs_baseline is MFU / 0.45 (the north-star ≥45% target).

Runs on whatever backend is active; on non-TPU hosts it shrinks the model so
the line is still produced (CI smoke), flagged via "device".
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def transformer_train_flops(L, h, V, batch, seq, ratio=4):
    """Forward+backward matmul FLOPs per step (2 flops per MAC, bwd = 2x fwd)."""
    per_layer_fwd = (
        6 * seq * h * h      # qkv projection
        + 2 * seq * h * h    # attention out projection
        + 4 * seq * seq * h  # QK^T and PV
        + 4 * ratio * seq * h * h  # MLP in+out
    )
    heads_fwd = 2 * seq * (h * h + h * V)  # mlm transform + tied decoder
    fwd = L * per_layer_fwd + heads_fwd
    return 3 * fwd * batch


PEAK_BF16 = {
    # chip kind (jax.devices()[0].device_kind) -> peak bf16 FLOP/s
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def main():
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu")
    on_tpu = "TPU" in str(kind).upper() or dev.platform in ("tpu", "axon")
    peak = PEAK_BF16.get(kind, 197e12 if on_tpu else 1e12)

    from hetu_tpu.core import set_random_seed
    from hetu_tpu.exec import Trainer
    from hetu_tpu.models import BertForPreTraining, bert_large, bert_base
    from hetu_tpu.ops.pallas import flash_attn_fn
    from hetu_tpu.optim import AdamWOptimizer

    set_random_seed(0)
    if on_tpu:
        cfg = bert_large(dtype=jnp.bfloat16)
        # batch swept on v5e with chunked timing: 192→.584, 224→.559, 256→.543
        # (>256 OOMs; ≤160 underfills the MXU)
        batch, seq, chunk = 192, 128, 5
    else:  # smoke fallback
        cfg = bert_base(num_layers=2, hidden_size=128, num_heads=2,
                        vocab_size=8192, dtype=jnp.float32)
        batch, seq, chunk = 8, 64, 2

    # Flash attention only pays off at long sequences; at seq 128 XLA's fused
    # plain attention is faster (kernel-launch bound), so gate on seq.
    use_flash = on_tpu and seq >= 512
    model = BertForPreTraining(
        cfg, attn_fn=flash_attn_fn(interpret=False) if use_flash else None)

    def loss_fn(model, batch_, key):
        loss, aux = model.loss(
            batch_["input_ids"], batch_["token_type"], None,
            batch_["mlm_labels"], batch_["nsp_labels"], key=key,
            training=False,  # dropout off for a deterministic perf path
        )
        return loss, {}

    trainer = Trainer(model, AdamWOptimizer(1e-4, weight_decay=0.01), loss_fn)

    rng = np.random.default_rng(0)
    b = {
        "input_ids": jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32),
        "token_type": jnp.zeros((batch, seq), jnp.int32),
        "mlm_labels": jnp.asarray(
            np.where(rng.random((batch, seq)) < 0.15,
                     rng.integers(0, cfg.vocab_size, (batch, seq)), -1),
            jnp.int32,
        ),
        "nsp_labels": jnp.asarray(rng.integers(0, 2, (batch,)), jnp.int32),
    }

    key = jax.random.key(0)
    # warmup/compile.  NOTE: block_until_ready does not actually block
    # through the axon TPU tunnel — a device→host transfer (float()) is the
    # only reliable sync, and that sync costs ~130 ms of tunnel round-trip.
    # Real training loops don't host-sync every step, so time CHUNKS of
    # steps with one sync per chunk (amortizes the tunnel latency) and take
    # the best chunk mean — robust to the occasional tunnel stall (long
    # unsynced queues were observed to degrade ~10x, so chunks stay short).
    for _ in range(3):
        m = trainer.step(b, key=key)
    float(m["loss"])
    per = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(chunk):
            m = trainer.step(b, key=key)
        float(m["loss"])
        per.append((time.perf_counter() - t0) / chunk)
    dt = float(min(per))

    flops = transformer_train_flops(
        cfg.num_layers, cfg.hidden_size, cfg.vocab_size, batch, seq,
        cfg.intermediate_ratio,
    )
    mfu = flops / dt / peak
    samples_per_sec = batch / dt
    print(json.dumps({
        "metric": "bert_large_pretrain_mfu" if on_tpu else "bert_smoke_mfu",
        "value": round(float(mfu), 4),
        "unit": "MFU",
        "vs_baseline": round(float(mfu) / 0.45, 4),
        "samples_per_sec_per_chip": round(samples_per_sec, 2),
        "step_ms": round(dt * 1e3, 2),
        "device": str(kind),
        "batch": batch, "seq": seq,
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # one retry: the tunneled TPU backend occasionally drops a compile
        # RPC; a transient hiccup should not cost the round's bench record
        import traceback
        traceback.print_exc()
        print("bench: retrying once after failure", file=sys.stderr)
        main()
