"""Benchmarks: the five BASELINE configs (six metric lines) on one chip.

Emits one JSON line per config ({"metric", "value", "unit", "vs_baseline",
...}), the headline BERT-large pretrain MFU LAST (drivers that parse the
final line record the north-star metric).  Configs (BASELINE.md):

  1. resnet18_cifar_steps_per_sec   — examples/cnn/scripts/hetu_1gpu.sh
  2. wdl_ctr_steps_per_sec          — examples/ctr/tests/hybrid_wdl_*.sh,
                                      host HET-cached embedding under load
  3. moe_samples_per_sec            — examples/moe/scripts/run_top1.sh
  4. gpt_autoparallel_samples_per_sec — profile -> plan -> train
  5. bert_large_seq512_mfu          — long-sequence path; attention core
                                      ({flash, xla-bhsd} x fused-LN) and
                                      batch-48+remat probed per run
  6. bert_large_pretrain_mfu        — headline; honest training step
                                      (dropout ON, key threaded);
                                      fused-LN probed per run

Timing: DEVICE time via a differenced compiled scan (Trainer.scan_steps):
one dispatch runs a lax.scan of k (then 2k) train steps, and
(t_2k - t_k)/k cancels the fixed per-dispatch host/tunnel cost exactly.
This is what makes the numbers regression-detectable — wall timing of
short steps over the tunnel swung 2x run to run (ResNet r03: 42-83
steps/s) because it measured dispatch jitter, not the framework.  Two
exceptions: the CTR config, whose per-step host embedding staging/push
IS the measured path (chunked wall timing, one sync per chunk, extra
reps), and the off-TPU smoke tier, where XLA:CPU takes minutes to
compile a scanned train step and the numbers are not perf claims.  Reported value uses the MEDIAN (min also recorded), and
every line carries "spread" = median/best so a noisy measurement is
visible in the artifact.  vs_baseline is MFU/0.45 (the north-star) where
MFU is defined; configs with no published reference number record
vs_baseline 1.0 and note that this round's value sets the baseline.

Runs on whatever backend is active; non-TPU hosts shrink shapes so every
line is still produced (CI smoke), flagged via "device".
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

# The per-config flops model and peak-FLOP/s table live in
# hetu_tpu.obs.goodput now, so the online MFU gauge and this benchmark
# report are the same arithmetic; re-exported here for callers/tests
# that import them from bench.
from hetu_tpu.obs.goodput import (PEAK_BF16, peak_flops,  # noqa: E402,F401
                                  transformer_train_flops)


def _env():
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu")
    on_tpu = "TPU" in str(kind).upper() or dev.platform in ("tpu", "axon")
    # peak_flops warns ONCE when an unknown TPU kind falls back to the
    # v5e figure — an MFU against a guessed peak must not be silent
    peak = peak_flops()
    return on_tpu, str(kind), peak


def timed_chunks(step, sync, *, chunk: int, reps: int = 3,
                 warmup: int = 3) -> dict:
    """Per-step seconds over ``reps`` chunks of ``chunk`` steps, one host
    sync per chunk.  Returns median (the reported number) and min.  Wall
    time — only for paths with intrinsic per-step host work (CTR)."""
    for _ in range(warmup):
        out = step()
    sync(out)
    per = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(chunk):
            out = step()
        sync(out)
        per.append((time.perf_counter() - t0) / chunk)
    med, mn = float(np.median(per)), float(min(per))
    return {"median_s": med, "min_s": mn,
            "spread": round(med / mn, 4) if mn > 0 else None,
            "timing": "wall-chunked"}


def timed_scan_diff(trainer, batch, *, k: int, reps: int = 4,
                    key=None) -> dict:
    """Device seconds per train step, measured as a differenced compiled
    scan: run(k steps) and run(2k steps) are each ONE dispatch, so
    (t_2k - t_k)/k cancels the fixed dispatch/tunnel cost exactly (same
    number of host round trips on both sides of the difference).  Sync is
    float(loss) — block_until_ready is a no-op through the tunnel.  The
    trainer's state advances (3*k*(reps+1) real steps) and is handed
    back, so subsequent use sees the trained state."""
    run_k = trainer.scan_steps(k)
    run_2k = trainer.scan_steps(2 * k)
    key = jax.random.key(1) if key is None else key
    state = trainer.state
    last = {}

    def call(run):
        nonlocal state, last
        t0 = time.perf_counter()
        state, last = run(state, batch, key)
        float(last["loss"])
        return time.perf_counter() - t0

    call(run_k)
    call(run_2k)  # compile + warm both programs
    call(run_k)
    call(run_2k)  # one throwaway pair: the first post-compile execution
    # of a program can run ~30% slow (autotune/cache residue) and a
    # polluted t_k skews the whole differenced pair (seen on the
    # autoparallel config: rep-0 diff 64 ms vs steady 108 ms)
    diffs, fixed = [], []
    for _ in range(reps):
        t1 = call(run_k)
        t2 = call(run_2k)
        diffs.append((t2 - t1) / k)
        fixed.append(2 * t1 - t2)  # per-dispatch overhead estimate
    trainer.state = state
    med, mn = float(np.median(diffs)), float(min(diffs))
    return {"median_s": med, "min_s": mn,
            "spread": round(med / mn, 4) if mn > 0 else None,
            "dispatch_ms": round(float(np.median(fixed)) * 1e3, 1),
            "last_metrics": last,  # final step's full metrics, no extra
            # dispatch or compile (scan_steps returns them)
            "timing": "scan-diff-device"}


def timed_step(trainer, batch, *, k: int, on_tpu: bool, key=None) -> dict:
    """scan-diff device timing on TPU; chunked wall timing off-TPU (the
    CPU smoke tier: XLA:CPU takes minutes to compile a scanned conv/
    transformer train step, and the smoke numbers are not perf claims)."""
    if on_tpu:
        return timed_scan_diff(trainer, batch, k=k, key=key)
    kw = {} if key is None else {"key": key}
    return timed_chunks(lambda: trainer.step(batch, **kw),
                        lambda m: float(m["loss"]), chunk=max(2, k))


def _tinfo(t):
    """Timing-quality fields every metric line carries."""
    out = {"timing": t["timing"], "spread": t["spread"]}
    if "dispatch_ms" in t:
        out["dispatch_ms"] = t["dispatch_ms"]
    return out


def _numerics_fields(trainer, batch, key=None):
    """Grad-norm / nonfinite health summary for a train metric line
    (obs.numerics.grad_health): a perf regression that is really a
    numerics regression — exploding group, NaN factory — names the
    unhealthy layer in the same JSON artifact.  Costs one extra gradient
    compile on the measured config; HETU_TPU_BENCH_NUMERICS=0 skips."""
    if os.environ.get("HETU_TPU_BENCH_NUMERICS", "1") in ("0", "false"):
        return {}
    try:
        from hetu_tpu.obs.numerics import grad_health
        return {"numerics": grad_health(trainer.loss_fn,
                                        trainer.state.model, batch,
                                        key)}
    except Exception as e:  # a health probe must never kill the line
        return {"numerics": {"error": str(e)[:120]}}


_CONTROLLER_SUMMARY = None


def _controller_fields():
    """Closed-loop remediation summary for train lines
    (exec.controller.controller_smoke): a seeded 2-worker in-process
    deadline-retune smoke — actions taken and the final tuned deadline
    prove the telemetry->actuator loop is live on this build, in the
    same JSON artifact as the perf number.  Deterministic, memoized
    (one run per bench process), and — like every bench config — only
    reached past the rc=3 device preflight.
    HETU_TPU_BENCH_CONTROLLER=0 skips."""
    global _CONTROLLER_SUMMARY
    if os.environ.get("HETU_TPU_BENCH_CONTROLLER", "1") in ("0", "false"):
        return {}
    if _CONTROLLER_SUMMARY is None:
        try:
            from hetu_tpu.exec.controller import controller_smoke
            _CONTROLLER_SUMMARY = {"controller": controller_smoke()}
        except Exception as e:  # the smoke must never kill the line
            _CONTROLLER_SUMMARY = {"controller": {"error": str(e)[:120]}}
    return _CONTROLLER_SUMMARY


_CALIB_STORE = None


def _calib_record(rec):
    """Append one calibration record per emitted result line — the
    measure side of the calibration plane (obs.calibration): the round's
    numbers land in the versioned profile store, where the sentinel
    grades them against the stored baseline and journals
    ``perf_regression`` on a >10% throughput/MFU drop — the alarm rounds
    4-5 (backend_unreachable) never had.  Uses the installed process
    store when one is, else the env-pathed on-disk store
    (HETU_TPU_CALIB_STORE).  HETU_TPU_BENCH_CALIB=0 skips; like every
    metric line, this only runs past the rc=3 device preflight, so a
    dead tunnel can never write a bogus baseline."""
    global _CALIB_STORE
    if os.environ.get("HETU_TPU_BENCH_CALIB", "1") in ("0", "false"):
        return
    try:
        from hetu_tpu.obs import calibration as _calibration
        store = _calibration.get_store()
        if store is None:
            if _CALIB_STORE is None:
                # LOAD, not construct: each bench run is a fresh process,
                # and the sentinel grades against the key's version-1
                # baseline — an empty store would re-baseline every round
                # and the cross-round alarm would never fire.  A damaged
                # store file must not kill the line: start fresh at the
                # same path (the damage is diagnosed on any explicit load).
                path = _calibration.default_store_path()
                try:
                    _CALIB_STORE = _calibration.ProfileStore.load(path)
                except _calibration.CalibrationStoreError as e:
                    print(f"bench: calibration store unreadable "
                          f"({e}); starting fresh", file=sys.stderr)
                    _CALIB_STORE = _calibration.ProfileStore(path)
            store = _CALIB_STORE
        store.ingest_bench_line(rec)
    except Exception as e:  # a calibration hiccup must never kill the line
        print(f"bench: calibration record skipped: {e}", file=sys.stderr)


def _line(metric, value, unit, vs_baseline, **extra):
    rec = {"metric": metric, "value": round(float(value), 4), "unit": unit,
           "vs_baseline": round(float(vs_baseline), 4), **extra}
    print(json.dumps(rec))
    sys.stdout.flush()
    _calib_record(rec)
    return rec


# ---------------------------------------------------------------------------
# config 1: ResNet-18 / CIFAR-10, single device
# ---------------------------------------------------------------------------

def bench_resnet(on_tpu, kind, peak):
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.exec import Trainer
    from hetu_tpu.models import resnet18
    from hetu_tpu.optim import MomentumOptimizer
    from hetu_tpu.ops import softmax_cross_entropy_sparse

    set_random_seed(0)
    batch, k = (128, 40) if on_tpu else (16, 3)
    model = resnet18(num_classes=10)

    def loss_fn(model, b, key):
        logits, new_model = model(b["x"], training=True)
        loss = softmax_cross_entropy_sparse(logits, b["y"]).mean()
        return loss, {"model": new_model}

    trainer = Trainer(model, MomentumOptimizer(0.1, momentum=0.9), loss_fn)
    rng = np.random.default_rng(0)
    b = {"x": jnp.asarray(rng.standard_normal((batch, 32, 32, 3)),
                          jnp.float32),
         "y": jnp.asarray(rng.integers(0, 10, (batch,)), jnp.int32)}
    t = timed_step(trainer, b, k=k, on_tpu=on_tpu)
    return _line(
        "resnet18_cifar_steps_per_sec", 1.0 / t["median_s"], "steps/s", 1.0,
        samples_per_sec=round(batch / t["median_s"], 1),
        best_steps_per_sec=round(1.0 / t["min_s"], 2),
        baseline_note="device time (differenced scan); r03 wall numbers "
                      "(42-83 steps/s) measured tunnel dispatch, not the "
                      "framework — this line is the regression baseline",
        device=kind, batch=batch, **_numerics_fields(trainer, b),
        **_controller_fields(), **_tinfo(t))


# ---------------------------------------------------------------------------
# config 2: Wide&Deep CTR with the HET host-embedding cache (hybrid path)
# ---------------------------------------------------------------------------

def _ctr_cfg(on_tpu, embedding: str, storage: str = "f32"):
    """The wdl_ctr workload config for one A/B arm.  ``host`` is the
    standing baseline (HET host cache); ``tiered`` layers the HBM hot-row
    budget + touch-gated promotion on the same host cache
    (embed.TieredEmbedding), optionally over int8 PS storage."""
    from hetu_tpu.models import CTRConfig

    vocab = 26000 if on_tpu else 2000
    # cache sized to the working set: a 4096-row cache thrashed on the
    # 26k-vocab batches and cost 3.3x (engine pulls on every miss)
    # host_async_push = the reference PS default (ASP, bsp=-1): the
    # gradient push's device->host round trip hides under the next step
    # instead of serializing every step — 2.9 -> 3.9 steps/s on the
    # tunneled chip (r03 A/B)
    host_cache = 65536 if on_tpu else 2048
    if embedding == "tiered":
        # HBM budget sized to the hot set (zipf head), host tier at the
        # host arm's width so the PS traffic comparison is apples-to-
        # apples; async push does not apply (the HBM layer pushes grads
        # through the host cache synchronously, off the gather path)
        # pull_bound=2 = HET's bounded staleness on the device tier: a
        # hot row serves its HBM copy for up to 2 server updates before
        # re-pulling — the amortization the tier exists for (VLDB'22);
        # strict-freshness parity is covered by the deterministic tests
        return CTRConfig(vocab=vocab, embed_dim=16, embedding="tiered",
                         cache_capacity=8192 if on_tpu else 512,
                         host_cache_capacity=host_cache,
                         cache_policy="lfuopt", host_optimizer="adagrad",
                         host_lr=0.05, storage=storage, pull_bound=2,
                         promote_touches=2, demote_idle=0)
    return CTRConfig(vocab=vocab, embed_dim=16, embedding="host",
                     cache_capacity=host_cache,
                     cache_policy="lfuopt", host_optimizer="adagrad",
                     host_lr=0.05, host_async_push=bool(on_tpu),
                     storage=storage)


def _ctr_time(on_tpu, cfg):
    """Build + time the wdl_ctr workload under ``cfg``; returns
    ``(timing, trainer, batch_size)``."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.data.datasets import synthetic_ctr
    from hetu_tpu.exec import Trainer
    from hetu_tpu.models import WideDeep
    from hetu_tpu.optim import AdamOptimizer

    set_random_seed(0)
    batch, chunk = (512, 10) if on_tpu else (64, 2)
    model = WideDeep(cfg)
    data = synthetic_ctr(n=batch * 8, vocab_per_field=cfg.vocab // 26)
    trainer = Trainer(
        model, AdamOptimizer(1e-3),
        lambda m, b, k: m.loss(b["dense"], b["sparse"], b["label"]))
    n = len(data["label"])
    state = {"i": 0}

    def step():
        lo = (state["i"] * batch) % (n - batch)
        state["i"] += 1
        b = {k: jnp.asarray(v[lo:lo + batch]) for k, v in data.items()}
        for m_ in trainer.staged_modules():
            m_.stage(b["sparse"])  # served from the prefetch buffer when warm
        out = trainer.step(b)
        nxt = (state["i"] * batch) % (n - batch)
        for m_ in trainer.staged_modules():
            m_.prefetch(data["sparse"][nxt:nxt + batch])  # overlap next pull
        return out

    # wall timing stays CORRECT here: the per-step host staging/push IS the
    # measured path (it cannot live inside a compiled scan); 5 reps damp
    # tunnel jitter instead
    t = timed_chunks(step, lambda m: float(m["loss"]), chunk=chunk, reps=5)
    for m_ in trainer.staged_modules():
        m_.stage(data["sparse"][(state["i"] * batch) % (n - batch):]
                 [:batch])  # retire the final pending prefetch
    return t, trainer, batch


def bench_ctr(on_tpu, kind, peak):
    t, trainer, batch = _ctr_time(on_tpu, _ctr_cfg(on_tpu, "host"))
    return _line(
        "wdl_ctr_steps_per_sec", 1.0 / t["median_s"], "steps/s", 1.0,
        samples_per_sec=round(batch / t["median_s"], 1),
        best_steps_per_sec=round(1.0 / t["min_s"], 2),
        baseline_note="host HET-cache embedding path under load; no "
                      "published reference number, this round's value sets "
                      "the baseline",
        device=kind, batch=batch, embedding="host+lfuopt-cache",
        **_controller_fields(), **_tinfo(t))


def bench_ctr_tiered(on_tpu, kind, peak, storage: str = "f32"):
    """Tiered-vs-host wdl_ctr A/B (``--mode ctr --embedding tiered``):
    both arms run the SAME seeded workload, vs_baseline = tiered/host
    steps/s, and the line carries the tiered arm's exact per-tier hit
    accounting (plus an ``embed`` calibration record when a store is
    installed), so the win is attributable, not vibes."""
    t_host, _, batch = _ctr_time(on_tpu, _ctr_cfg(on_tpu, "host"))
    t_tier, trainer, _ = _ctr_time(
        on_tpu, _ctr_cfg(on_tpu, "tiered", storage=storage))
    tier_stats = {}
    for m_ in trainer.staged_modules():
        ts = getattr(m_, "tier_stats", None)
        if ts is not None:
            tier_stats = ts()
            break
    if tier_stats:
        from hetu_tpu.obs import calibration as _calibration
        store = _calibration.get_store()
        if store is not None and os.environ.get(
                "HETU_TPU_BENCH_CALIB", "1") != "0":
            store.ingest_embed(tier_stats, model_sig="wdl_ctr",
                               device_kind=kind)
    host_sps = 1.0 / t_host["median_s"]
    tier_sps = 1.0 / t_tier["median_s"]
    return _line(
        "wdl_ctr_tiered_steps_per_sec", tier_sps, "steps/s",
        tier_sps / host_sps if host_sps > 0 else 1.0,
        samples_per_sec=round(batch / t_tier["median_s"], 1),
        host_steps_per_sec=round(host_sps, 2),
        storage=storage,
        hbm_hit_rate=(round(tier_stats["hbm"]["hit_rate"], 4)
                      if tier_stats else None),
        host_hit_rate=(round(tier_stats["host"]["hit_rate"], 4)
                       if tier_stats else None),
        pull_bytes_per_stage=(round(tier_stats["pull_bytes_per_stage"], 1)
                              if tier_stats else None),
        ps_resident_bytes=(tier_stats["ps"]["resident_bytes"]
                           if tier_stats else None),
        baseline_note="vs_baseline = tiered/host steps/s on the same "
                      "seeded wdl_ctr workload; hit rates are the tiered "
                      "arm's exact per-tier counters",
        device=kind, batch=batch, embedding=f"tiered+{storage}",
        **_controller_fields(), **_tinfo(t_tier))


# ---------------------------------------------------------------------------
# config 3: MoE transformer (gates + capacity dispatch; EP collapses to one
# expert group on a single chip — the multi-chip EP path is exercised by
# dryrun_multichip config B and tests)
# ---------------------------------------------------------------------------

def bench_moe(on_tpu, kind, peak):
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.exec import Trainer
    from hetu_tpu.models.moe_lm import MoELM, MoELMConfig
    from hetu_tpu.optim import AdamOptimizer

    set_random_seed(0)
    if on_tpu:
        batch, seq, k = 32, 256, 8
        # capacity 1.25 (explicit; the standard top-1 Switch setting —
        # cap 2.0 measured 346 vs 428 samples/s on one v5e)
        # routing observability ON (the reference logs gate accounting
        # too): overflow_frac / load_entropy ride the metric line so a
        # silently-collapsing router is visible in the bench artifact
        cfg = MoELMConfig(vocab_size=32000, hidden_size=1024, num_layers=4,
                          num_heads=16, num_experts=8, top_k=1,
                          capacity_factor=1.25, max_seq_len=seq,
                          log_routing_stats=True, dtype=jnp.bfloat16)
    else:
        batch, seq, k = 4, 64, 2
        cfg = MoELMConfig(vocab_size=500, hidden_size=64, num_layers=2,
                          num_heads=4, num_experts=4, top_k=1,
                          max_seq_len=seq)
    model = MoELM(cfg)
    trainer = Trainer(model, AdamOptimizer(1e-4),
                      lambda m, b, k: m.loss(b["ids"], training=True))
    rng = np.random.default_rng(0)
    b = {"ids": jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                            jnp.int32)}
    t = timed_step(trainer, b, k=k, on_tpu=on_tpu)
    # routing stats ride the timed scan's final metrics — no extra
    # compile/dispatch (off-TPU, log_routing_stats is off and this is {})
    m = t.get("last_metrics", {})
    stats = {k2: round(float(m[k2]), 4)
             for k2 in ("overflow_frac", "load_entropy") if k2 in m}
    return _line(
        "moe_samples_per_sec", batch / t["median_s"], "samples/s", 1.0,
        best_samples_per_sec=round(batch / t["min_s"], 1),
        baseline_note="reference run_top1.sh ships no table; this round's "
                      "value sets the baseline",
        device=kind, batch=batch, seq=seq, experts=cfg.num_experts,
        top_k=cfg.top_k, **stats, **_controller_fields(), **_tinfo(t))


# ---------------------------------------------------------------------------
# config 4: auto-parallel GPT — profile -> dp_search plan -> train with the
# materialized strategy
# ---------------------------------------------------------------------------

def bench_autogpt(on_tpu, kind, peak):
    import dataclasses

    from hetu_tpu.core import set_random_seed
    from hetu_tpu.exec import Trainer
    from hetu_tpu.models import GPT, GPTConfig
    from hetu_tpu.optim import AdamOptimizer
    from hetu_tpu.parallel.autoparallel import (
        ClusterSpec, CostProfiler, dp_search, plan_to_strategy,
        transformer_layer_spec)
    from hetu_tpu.parallel.mesh import make_mesh
    from hetu_tpu.parallel.strategies import ShardingStrategy

    set_random_seed(0)
    if on_tpu:
        batch, seq, hidden, layers, k = 32, 512, 1024, 8, 5
        cluster = dataclasses.replace(CostProfiler().calibrate(),
                                      n_devices=len(jax.devices()))
    else:
        batch, seq, hidden, layers, k = 4, 64, 64, 2, 2
        cluster = ClusterSpec(n_devices=len(jax.devices()), hbm_bytes=16e9)
    specs = [transformer_layer_spec(hidden, seq, name=f"l{i}")
             for i in range(layers)]
    plan = dp_search(specs, cluster, global_batch=batch)
    mesh_spec, kwargs = plan_to_strategy(plan)
    mesh = make_mesh(mesh_spec)
    cfg = GPTConfig(vocab_size=32000 if on_tpu else 500, hidden_size=hidden,
                    num_layers=layers, num_heads=hidden // 64,
                    max_seq_len=seq,
                    dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    strategy = ShardingStrategy(mesh=mesh, **kwargs)
    from hetu_tpu.ops.pallas import flash_attn_fn
    # the raw Pallas kernel has no SPMD partitioning rule: only safe when
    # the searched plan is single-device (sharded plans would need the
    # shard_map-wrapped ring/ulysses cores)
    use_flash = on_tpu and mesh_spec.total() == 1
    trainer = Trainer(
        GPT(cfg, attn_fn=(flash_attn_fn(native_layout=True)
                          if use_flash else None)),
        AdamOptimizer(3e-4),
        lambda m, b, k: (m.loss(b["ids"], key=k, training=True), {}),
        strategy=strategy)
    rng = np.random.default_rng(0)
    b = {"ids": jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                            jnp.int32)}
    t = timed_step(trainer, b, k=k, on_tpu=on_tpu)
    flops = transformer_train_flops(layers, hidden, cfg.vocab_size, batch,
                                    seq)
    mfu = flops / t["median_s"] / peak
    return _line(
        "gpt_autoparallel_samples_per_sec", batch / t["median_s"],
        "samples/s", mfu / 0.45 if on_tpu else 1.0,
        mfu=round(float(mfu), 4), plan=plan.describe(),
        best_samples_per_sec=round(batch / t["min_s"], 1),
        device=kind, batch=batch, seq=seq, **_controller_fields(),
        **_tinfo(t))


# ---------------------------------------------------------------------------
# configs 5+6: BERT-large pretraining (long-seq flash + headline)
# ---------------------------------------------------------------------------

_PROBE_K = 3  # scan length of A/B probes; a config whose own k matches
# reuses its winning probe as the full measurement (no recompile)

_T0 = time.perf_counter()
# Optional work (variant probes, block autotuning) is skipped once the
# run is this old, so a slow tunnel can delay but never starve the later
# configs — the headline line must always be produced.
_SOFT_DEADLINE_S = float(os.environ.get("HETU_BENCH_SOFT_DEADLINE_S", 1800))


def _behind_schedule() -> bool:
    late = time.perf_counter() - _T0 > _SOFT_DEADLINE_S
    if late:
        print("bench: soft deadline passed - skipping optional probes",
              file=sys.stderr)
    return late


def _bert_time(on_tpu, kind, peak, *, seq, batch, k, attn, fused_ln,
               remat=False):
    """Build a fresh BERT trainer with the given (attention core, fused_ln)
    variant and return the timing dict (+ config/flops context).
    attn: "flash" = Pallas kernel, "xla" = materialized bhsd core."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.exec import Trainer
    from hetu_tpu.layers.attention import dot_product_attention_bhsd
    from hetu_tpu.models import BertForPreTraining, bert_base, bert_large
    from hetu_tpu.ops.pallas import flash_attn_fn
    from hetu_tpu.optim import AdamWOptimizer

    set_random_seed(0)
    if on_tpu:
        cfg = bert_large(max_position_embeddings=max(512, seq),
                         fused_ln=fused_ln, remat=remat, dtype=jnp.bfloat16)
    else:
        cfg = bert_base(num_layers=2, hidden_size=128, num_heads=2,
                        vocab_size=8192, fused_ln=fused_ln, remat=remat,
                        dtype=jnp.float32)
        batch, seq, k = 8, 64, 2
    # the native (B,H,S,D) einsum projection path pays off for BOTH cores:
    # flash at seq 512, and the XLA materialized core at seq 128 (0.634 ->
    # 0.658 MFU: the qkv split/relayout copies vanish)
    model = BertForPreTraining(
        cfg, attn_fn=(flash_attn_fn(native_layout=True) if attn == "flash"
                      else dot_product_attention_bhsd) if on_tpu else None)

    def loss_fn(model, b, key):
        # honest training step: dropout ON, RNG key threaded
        loss, aux = model.loss(
            b["input_ids"], b["token_type"], None,
            b["mlm_labels"], b["nsp_labels"], key=key, training=True)
        return loss, {}

    trainer = Trainer(model, AdamWOptimizer(1e-4, weight_decay=0.01),
                      loss_fn)
    rng = np.random.default_rng(0)
    b = {
        "input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32),
        "token_type": jnp.zeros((batch, seq), jnp.int32),
        "mlm_labels": jnp.asarray(
            np.where(rng.random((batch, seq)) < 0.15,
                     rng.integers(0, cfg.vocab_size, (batch, seq)), -1),
            jnp.int32),
        "nsp_labels": jnp.asarray(rng.integers(0, 2, (batch,)), jnp.int32),
    }
    t = timed_step(trainer, b, k=k, on_tpu=on_tpu)
    t["flops"] = transformer_train_flops(
        cfg.num_layers, cfg.hidden_size, cfg.vocab_size, batch, seq,
        cfg.intermediate_ratio)
    t["batch"], t["seq"] = batch, seq
    # handed back (and stripped before the JSON line) so the winning
    # variant's metric line can carry the grad-health summary without a
    # second trainer build
    t["_trainer"], t["_batch"] = trainer, b
    return t


def _bert_mfu(on_tpu, kind, peak, *, seq, batch, k, variants, metric,
              remat_batch=None):
    """Measure each (attn, fused_ln) variant with a short probe, emit the
    full-length winner.  This is how perf decisions stay MEASURED per
    round instead of frozen: r04's fused-LN kernel was
    interpreter-validated but the tunnel died before any on-chip A/B
    (TPU_CHECKS_r04), so the flag choice lives HERE, decided on the chip
    the driver actually runs — and the losing variants' numbers ride the
    artifact line (reference composes LayerNorm.cu + Dropout.cu as
    discrete kernels either way)."""
    ab, probes = {}, {}
    if on_tpu and len(variants) > 1 and _behind_schedule():
        variants = variants[:1]  # measured default; probes skipped
    if on_tpu and len(variants) > 1:
        for attn, fl in variants:
            tag = f"{attn}{'+fln' if fl else ''}"
            try:
                p = _bert_time(on_tpu, kind, peak, seq=seq, batch=batch,
                               k=_PROBE_K, attn=attn, fused_ln=fl)
                probes[(attn, fl)] = p
                ab[tag] = round(p["median_s"] * 1e3, 2)
            except Exception as e:
                # a variant that deterministically cannot compile/run is
                # disqualified with its error in the artifact; transient
                # tunnel blips must NOT silently disqualify — re-raise into
                # main()'s per-config transient retry
                if any(s in str(e).lower() for s in _TRANSIENT):
                    raise
                traceback.print_exc()
                ab[tag] = f"failed: {str(e)[:120]}"
        if not probes:
            raise RuntimeError(f"all bert variants failed: {ab}")
        attn, fused_ln = min(probes, key=lambda v: probes[v]["median_s"])
    else:
        (attn, fused_ln), = variants[:1]
    remat = False
    if ab and remat_batch and remat_batch > batch:
        # the winner at the memory-capped batch vs the SAME variant at a
        # larger batch with per-block rematerialization (exact numerics,
        # ~1/3 more backward FLOPs for O(layers) activation memory):
        # whichever moves more samples/sec wins.  An OOM at the larger
        # batch just disqualifies the candidate.
        try:
            pr = _bert_time(on_tpu, kind, peak, seq=seq, batch=remat_batch,
                            k=_PROBE_K, attn=attn, fused_ln=fused_ln,
                            remat=True)
            ab[f"b{remat_batch}+remat"] = round(pr["median_s"] * 1e3, 2)
            base = probes[(attn, fused_ln)]
            if (remat_batch / pr["median_s"]) > (batch / base["median_s"]):
                probes[(attn, fused_ln, "remat")] = pr
                batch, remat = remat_batch, True
        except Exception as e:
            if any(s in str(e).lower() for s in _TRANSIENT):
                raise
            traceback.print_exc()
            ab[f"b{remat_batch}+remat"] = f"failed: {str(e)[:120]}"
    key3 = (attn, fused_ln, "remat") if remat else (attn, fused_ln)
    if key3 in probes and k == _PROBE_K:
        t = probes[key3]  # the probe IS the full measurement
    else:
        t = _bert_time(on_tpu, kind, peak, seq=seq, batch=batch, k=k,
                       attn=attn, fused_ln=fused_ln, remat=remat)
    mfu = t["flops"] / t["median_s"] / peak
    trainer, b = t.pop("_trainer", None), t.pop("_batch", None)
    numerics = _numerics_fields(trainer, b) if trainer is not None else {}
    return _line(
        metric if on_tpu else "bert_smoke_mfu", mfu, "MFU", mfu / 0.45,
        samples_per_sec_per_chip=round(t["batch"] / t["median_s"], 2),
        step_ms=round(t["median_s"] * 1e3, 2),
        best_mfu=round(t["flops"] / t["min_s"] / peak, 4),
        dropout=True, flash_attention=(attn == "flash" and on_tpu),
        fused_ln=bool(fused_ln and on_tpu), remat=bool(remat),
        **({"ab_probe_ms": ab} if ab else {}), **numerics,
        **_controller_fields(),
        device=kind, batch=t["batch"], seq=t["seq"], **_tinfo(t))


# Ordered BEST-MEASURED-FIRST: when the soft deadline trips, _bert_mfu
# degrades to variants[0] without probing, so the head of this list must
# be the fastest variant a past round actually measured — the XLA bhsd
# core (TPU_CHECKS_r04: 225 ms vs r03 flash's 274 at seq 512).  A round
# that measures a new winner should rotate it to the front.
BERT512_VARIANTS = [("xla", False), ("flash", False),
                    ("xla", True), ("flash", True)]


def bench_bert_long(on_tpu, kind, peak):
    # batch 24: 48 (token parity with the seq-128 headline) OOMs on 16 GB —
    # seq-512 MLP activation temps are 4x larger per token batch.
    # Variants probed on-chip each run: the flash kernel vs the relayout-
    # free XLA bhsd core (TPU_CHECKS_r04 measured the latter at 225 ms vs
    # r03 flash's 274 — driver-unverified, hence measured HERE), each with
    # and without the fused-LN kernel.
    if on_tpu and not _behind_schedule():
        # measure this shape's flash blocks before the variant probes (the
        # kernel trace then picks the winner up from the persistent
        # cache); the budget bounds how many candidates run (checked
        # between candidates — a single in-flight compile cannot be
        # preempted), so a degraded tunnel costs at most ~one candidate
        # past budget
        from hetu_tpu.ops.pallas import autotune_flash_blocks
        try:
            e = autotune_flash_blocks(512, 512, 64, causal=False, batch=8,
                                      heads=16, budget_s=240)
            print(f"bench[bert512]: flash blocks autotuned -> "
                  f"{e['block_q']}x{e['block_k']}", file=sys.stderr)
        except Exception:
            traceback.print_exc()  # heuristic table still applies
    # remat_batch=48: seq-512 is memory-capped at batch 24 (48 OOMs on
    # 16 GB); per-block remat may buy the doubled batch back at ~1/3 more
    # backward FLOPs — probed, decided by samples/sec
    return _bert_mfu(on_tpu, kind, peak, seq=512, batch=24, k=3,
                     variants=BERT512_VARIANTS,
                     metric="bert_large_seq512_mfu", remat_batch=48)


def bench_bert_headline(on_tpu, kind, peak):
    # batch re-swept r03 with dropout ON: {64: 0.568, 96: 0.571, 128: 0.565,
    # 192: 0.531, 256: 0.495} — HBM pressure above ~128 degrades the whole
    # step (optimizer/LN fusions fall off roofline), so the r01 choice of
    # 192 was costing ~7% MFU.  Flash at seq 128 re-measured r03 and still
    # lost to XLA (0.461 vs 0.571) — kernel overhead swamps 128-wide
    # blocks; only the fused-LN choice is probed here (ROADMAP 4d).
    return _bert_mfu(on_tpu, kind, peak, seq=128, batch=96, k=5,
                     variants=[("xla", False), ("xla", True)],
                     metric="bert_large_pretrain_mfu")


# ---------------------------------------------------------------------------
# serve mode: seeded loadgen through the ServingEngine (paged vs gather)
# ---------------------------------------------------------------------------

def _hist_quantile(cum_before, cum_after, q: float):
    """Quantile from the delta of two cumulative-bucket snapshots —
    promoted into ``obs.registry.Histogram.quantile_from_cumulative``
    (the one quantile implementation in the tree; ``serve/engine.py``'s
    ``/stats`` summary uses the same code).  Kept as a thin alias for
    bench-internal callers and tests.  An empty delta reads ``nan``
    (deterministic — see the registry docstring); :func:`_q_or_none`
    maps that to a JSON-safe null for the metric line."""
    from hetu_tpu.obs.registry import Histogram
    return Histogram.quantile_from_cumulative(cum_before, cum_after, q)


def _q_or_none(v, digits: int = 6):
    """JSON has no NaN: empty-histogram quantiles become null."""
    return None if v is None or v != v else round(v, digits)


def _memory_section(snap):
    """The serve rounds' ``memory`` section from one
    :class:`~hetu_tpu.obs.memledger.MemoryLedger` snapshot: peak pool
    occupancy over the run, the shared-prefix fraction of the pages held
    at that peak, and the attributed high-water mark — the capacity
    numbers a planner sizes the fleet from."""
    pools = list(snap["kv_pools"].values())
    peak_pages = sum(p["peak_used_pages"] for p in pools)
    shared_pages = sum(p["peak_shared_pages"] for p in pools)
    return {
        "peak_pool_occupancy": round(
            max((p["peak_used_fraction"] for p in pools), default=0.0), 6),
        "shared_prefix_fraction": round(shared_pages / peak_pages, 6)
        if peak_pages else 0.0,
        "hwm_bytes": int(snap["hwm_bytes"].get("total", 0)),
    }


def _serve_run(cfg, trace, *, paged, num_slots, page_size, max_seq_len,
               buckets):
    """Drive one seeded trace through a fresh engine on the real clock;
    returns (decode tokens/s, ttft p50, ttft p99, completed,
    stage_decomposition) — the last is the SLO engine's per-stage
    summary over the measured window, so a regression names the stage
    that moved (queue vs prefill vs decode vs emit), not just a
    ratio."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.models import GPT
    from hetu_tpu.obs import memledger as _memledger
    from hetu_tpu.obs import registry as _obs
    from hetu_tpu.serve import ServingEngine

    set_random_seed(0)
    model = GPT(cfg)
    # a run-scoped ledger: peak pool occupancy + attributed HWM for the
    # metric line's memory section (restored on exit — the bench never
    # leaves a process-wide ledger behind)
    with _memledger.use(_memledger.MemoryLedger()) as led:
        eng = ServingEngine(model, num_slots=num_slots,
                            page_size=page_size, max_seq_len=max_seq_len,
                            prompt_buckets=buckets,
                            queue_depth=len(trace) + 1, sampling="top_k",
                            top_k=5, seed=11, paged_decode=paged)
        # warmup: compile the decode program AND every prefill bucket's
        # program outside the measured window (a serving fleet is warm;
        # TTFT here is SLO, not compile time — a single warmup request
        # would leave the other buckets' jit compiles inside the
        # measured histograms)
        for bucket in buckets:
            eng.submit(list(range(1, bucket + 1)), 2)
            eng.run_until_idle()
        hist = _obs.get_registry().histogram(
            "hetu_serve_ttft_seconds").labels()
        cum0 = hist.cumulative()
        # the warmup requests were graded too; summarize only the
        # measured window by differencing the SLO engine's stage totals
        stages0 = {s: v["total_s"]
                   for s, v in eng.slo.stage_summary().items()}
        n0 = eng.slo.requests
        handles = [eng.submit(list(it.prompt), it.max_new_tokens)
                   for it in trace]
        t0 = time.perf_counter()
        eng.run_until_idle(max_steps=10**7)
        dt = time.perf_counter() - t0
        cum1 = hist.cumulative()
        done = [h for h in handles if h.status == "completed"]
        stages1 = eng.slo.stage_summary()
        n = max(eng.slo.requests - n0, 1)
        totals = {s: stages1[s]["total_s"] - stages0[s] for s in stages1}
        wall = sum(totals.values())
        decomposition = {s: {"total_s": round(totals[s], 6),
                             "mean_s": round(totals[s] / n, 6),
                             "fraction": round(totals[s] / wall, 6)
                             if wall > 0 else 0.0}
                         for s in totals}
        # the first token of each request is prefill; the rest is decode
        decode_tokens = sum(max(len(h.tokens) - 1, 0) for h in done)
        memory = _memory_section(led.snapshot())
    return (decode_tokens / dt if dt > 0 else 0.0,
            _hist_quantile(cum0, cum1, 0.50),
            _hist_quantile(cum0, cum1, 0.99), len(done), decomposition,
            memory)


def bench_serve(on_tpu, kind, peak):
    """``--mode serve``: seeded open-loop load through the ServingEngine,
    one JSON line with decode tokens/s and TTFT p50/p99 from the serving
    SLO histograms — paged decode measured against the gather baseline on
    the same trace (the ROADMAP perf note's re-measure harness).  Runs
    behind the same fast-fail device preflight as the training configs
    (rc=3, no stdout metric on a dead tunnel)."""
    from hetu_tpu.models import GPTConfig
    from hetu_tpu.serve import generate_load

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        kw = dict(num_slots=8, page_size=64, max_seq_len=2048,
                  buckets=(128, 256, 512, 1024))
        trace = generate_load(17, 24, vocab=cfg.vocab_size,
                              prompt_len=(64, 1024), max_new=(32, 64),
                              mean_gap_s=0.0)
    else:  # CI smoke: tiny shapes, still the full two-path measurement
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64)
        kw = dict(num_slots=4, page_size=8, max_seq_len=64,
                  buckets=(8, 16))
        trace = generate_load(17, 8, vocab=cfg.vocab_size,
                              prompt_len=(2, 12), max_new=(2, 6),
                              mean_gap_s=0.0)
    paged_tps, p50, p99, done, stages, memory = _serve_run(
        cfg, trace, paged=True, **kw)
    gather_tps, g50, g99, gdone, gstages, _gmem = _serve_run(
        cfg, trace, paged=False, **kw)
    return _line(
        "serve_decode_tokens_per_sec", paged_tps, "tokens/s",
        paged_tps / gather_tps if gather_tps > 0 else 1.0,
        ttft_p50_s=_q_or_none(p50),
        ttft_p99_s=_q_or_none(p99),
        memory=memory,
        stage_decomposition=stages,
        gather_tokens_per_sec=round(gather_tps, 2),
        gather_ttft_p50_s=_q_or_none(g50),
        gather_ttft_p99_s=_q_or_none(g99),
        gather_stage_decomposition=gstages,
        requests=len(trace), completed=done, gather_completed=gdone,
        slots=kw["num_slots"], max_seq_len=kw["max_seq_len"],
        baseline_note="vs_baseline = paged/gather decode tokens/s on the "
                      "same seeded trace (acceptance bar 1.2x on-chip)",
        device=kind, timing="wall-trace", spread=None)


def bench_serve_fleet(on_tpu, kind, peak, *, replicas: int,
                      prefix_share: bool):
    """``--mode serve --replicas N [--prefix-share]``: the seeded
    SHARED-PREFIX trace (template pool × suffixes, loadgen satellite)
    through an N-replica FleetRouter — affinity placement, optional
    copy-on-write prefix sharing — against the same trace through a
    single replica.  One JSON line; ``vs_baseline`` = fleet / single
    decode tokens/s.  Rides the same rc=3 preflight as every serve
    round."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.models import GPT, GPTConfig
    from hetu_tpu.obs import registry as _obs
    from hetu_tpu.serve import (FleetRouter, ServingEngine,
                                generate_shared_prefix_load)

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        kw = dict(num_slots=8, page_size=64, max_seq_len=2048,
                  prompt_buckets=(128, 256, 512, 1024))
        trace = generate_shared_prefix_load(
            17, 24, vocab=cfg.vocab_size, n_templates=4, prefix_len=256,
            suffix_len=(16, 128), max_new=(32, 64), shared_fraction=0.7,
            unique_len=(64, 512), mean_gap_s=0.0)
    else:  # CI smoke: tiny shapes, still the full fleet-vs-single A/B
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64)
        kw = dict(num_slots=4, page_size=8, max_seq_len=64,
                  prompt_buckets=(8, 16, 32))
        trace = generate_shared_prefix_load(
            17, 12, vocab=cfg.vocab_size, n_templates=2, prefix_len=16,
            suffix_len=(2, 6), max_new=(2, 6), shared_fraction=0.7,
            unique_len=(4, 12), mean_gap_s=0.0)

    set_random_seed(0)
    model = GPT(cfg)
    hist = _obs.get_registry().histogram("hetu_serve_ttft_seconds").labels()

    def drive(n):
        from hetu_tpu.obs import memledger as _memledger
        with _memledger.use(_memledger.MemoryLedger()) as led:
            engines = [ServingEngine(model, queue_depth=len(trace) + 8,
                                     sampling="top_k", top_k=5, seed=11,
                                     prefix_sharing=prefix_share, **kw)
                       for _ in range(n)]
            router = FleetRouter(engines)
            # warmup: compile every prefill bucket on every replica
            # outside the measured window (the _serve_run convention)
            for eng in engines:
                for bucket in kw["prompt_buckets"]:
                    eng.submit(list(range(1, bucket + 1)), 2)
                eng.run_until_idle()
            cum0 = hist.cumulative()
            # open-loop-ish: one fleet tick between arrivals, so
            # published prefixes exist by the time their siblings route
            # (a burst would race every template request past the trie
            # it feeds)
            t0 = time.perf_counter()
            handles = []
            for it in trace:
                handles.append(router.submit(list(it.prompt),
                                             it.max_new_tokens))
                router.step()
            router.run_until_idle(max_steps=10**7)
            dt = time.perf_counter() - t0
            done = [h for h in handles if h.status == "completed"]
            decode_tokens = sum(max(len(h.tokens) - 1, 0) for h in done)
            memory = _memory_section(led.snapshot())
        return (decode_tokens / dt if dt > 0 else 0.0,
                _hist_quantile(cum0, hist.cumulative(), 0.50),
                _hist_quantile(cum0, hist.cumulative(), 0.99),
                len(done), router.stats(), memory)

    fleet_tps, p50, p99, done, fstats, memory = drive(replicas)
    single_tps, s50, s99, sdone, _, _smem = drive(1)
    return _line(
        "serve_fleet_decode_tokens_per_sec", fleet_tps, "tokens/s",
        fleet_tps / single_tps if single_tps > 0 else 1.0,
        replicas=replicas, prefix_share=prefix_share,
        ttft_p50_s=_q_or_none(p50), ttft_p99_s=_q_or_none(p99),
        memory=memory,
        single_tokens_per_sec=round(single_tps, 2),
        single_ttft_p50_s=_q_or_none(s50),
        single_ttft_p99_s=_q_or_none(s99),
        requests=len(trace), completed=done, single_completed=sdone,
        placements_by_reason=fstats["placements_by_reason"],
        pages_shared=fstats["pages_shared"],
        baseline_note="vs_baseline = fleet/single decode tokens/s on the "
                      "same seeded shared-prefix trace; in-process "
                      "replicas TIMESHARE this one device, so the ratio "
                      "isolates scheduling + prefix-sharing effects — "
                      "an N-chip deployment multiplies it by its "
                      "parallelism",
        device=kind, timing="wall-trace", spread=None)


def bench_serve_chaos(on_tpu, kind, peak):
    """``--mode serve --chaos``: the seeded replica-crash trace through a
    3-replica fleet with the failover monitor attached — one replica is
    crashed mid-decode by a seeded FaultPlan, its in-flight streams are
    re-homed, and the SAME trace runs crash-free for the baseline.  One
    JSON line: ``vs_baseline`` = chaos / crash-free decode tokens/s, plus
    the completion rate, the failover and re-home tallies, whether every
    stream (fingerprint included) matched the crash-free run bitwise, and
    the post-run export-hold count (zero = no KV page leaked across the
    failover).  Rides the same rc=3 preflight as every serve round."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.exec import faults as _faults
    from hetu_tpu.models import GPT, GPTConfig
    from hetu_tpu.obs import registry as _obs
    from hetu_tpu.serve import FleetRouter, ServingEngine, generate_load
    from hetu_tpu.serve.fleet.failover import FailoverMonitor

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        kw = dict(num_slots=8, page_size=64, max_seq_len=2048,
                  prompt_buckets=(128, 256, 512, 1024))
        trace = generate_load(29, 24, vocab=cfg.vocab_size,
                              prompt_len=(64, 1024), max_new=(32, 64),
                              mean_gap_s=0.0)
        crash_tick = 12
    else:  # CI smoke: tiny shapes, still the full chaos-vs-clean A/B
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64)
        kw = dict(num_slots=4, page_size=8, max_seq_len=64,
                  prompt_buckets=(8, 16, 32))
        trace = generate_load(29, 12, vocab=cfg.vocab_size,
                              prompt_len=(2, 12), max_new=(2, 8),
                              mean_gap_s=0.0)
        crash_tick = 6

    set_random_seed(0)
    model = GPT(cfg)
    hist = _obs.get_registry().histogram("hetu_serve_ttft_seconds").labels()

    def drive(plan):
        engines = [ServingEngine(model, queue_depth=len(trace) + 8,
                                 sampling="top_k", top_k=5, seed=11, **kw)
                   for _ in range(3)]
        router = FleetRouter(engines)
        monitor = FailoverMonitor(router, lease_ticks=3)
        # warmup: compile every prefill bucket on every replica outside
        # the measured window (the _serve_run convention); the monitor
        # only ticks under router.step(), so warmup consumes no faults
        for eng in engines:
            for bucket in kw["prompt_buckets"]:
                eng.submit(list(range(1, bucket + 1)), 2)
            eng.run_until_idle()
        cum0 = hist.cumulative()
        with _faults.inject(plan):
            t0 = time.perf_counter()
            # explicit ids keep sampling keys — hence streams — aligned
            # between the chaos and crash-free drives of the same trace
            handles = [router.submit(list(it.prompt), it.max_new_tokens,
                                     request_id=i)
                       for i, it in enumerate(trace)]
            router.run_until_idle(max_steps=10**7)
            dt = time.perf_counter() - t0
        done = [h for h in handles if h.status == "completed"]
        decode_tokens = sum(max(len(h.tokens) - 1, 0) for h in done)
        streams = [(h.status, tuple(h.tokens), h.stream_fingerprint)
                   for h in handles]
        held = sum(e.pool.stats()["pages_export_held"] for e in engines)
        return (decode_tokens / dt if dt > 0 else 0.0,
                _hist_quantile(cum0, hist.cumulative(), 0.99),
                len(done), streams, held, monitor)

    plan = _faults.FaultPlan(
        [(crash_tick, _faults.Fault("replica_crash", worker=0))])
    chaos_tps, p99, done, streams, held, monitor = drive(plan)
    clean_tps, c99, cdone, clean_streams, _cheld, _cmon = drive(
        _faults.FaultPlan([]))
    rehomed = sum(len(d["rehomed"]) for d in monitor.decisions)
    return _line(
        "serve_chaos_decode_tokens_per_sec", chaos_tps, "tokens/s",
        chaos_tps / clean_tps if clean_tps > 0 else 1.0,
        replicas=3, crash_tick=crash_tick,
        requests=len(trace), completed=done, clean_completed=cdone,
        completion_rate=round(done / len(trace), 4),
        failovers=len([d for d in monitor.decisions
                       if d["reason"] in ("crashed", "lease_expired")]),
        requests_rehomed=rehomed,
        bitwise_vs_crash_free=streams == clean_streams,
        pages_export_held=held,
        ttft_p99_s=_q_or_none(p99), clean_ttft_p99_s=_q_or_none(c99),
        baseline_note="vs_baseline = chaos/crash-free decode tokens/s on "
                      "the same seeded trace; acceptance: completion_rate "
                      "1.0, bitwise_vs_crash_free true, pages_export_held "
                      "0 — the failover plane re-homes without changing a "
                      "single sampled token or leaking a KV page",
        device=kind, timing="wall-trace", spread=None)


def bench_serve_disagg(on_tpu, kind, peak):
    """``--mode serve --disagg``: the seeded PREFILL-BURST trace (steady
    short-decode traffic + clumped long-prompt bursts, the workload
    where colocation loses) through a 1-prefill + 1-decode
    ``DisaggRouter`` against the same trace through two colocated
    engines — equal chips, arrivals interleaved with fleet ticks as in
    the PR 13 fleet bench.  One JSON line; ``vs_baseline`` = disagg /
    colocated decode tokens/s, with TTFT p99 for both modes alongside.
    Rides the same rc=3 preflight as every serve round."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.models import GPT, GPTConfig
    from hetu_tpu.obs import registry as _obs
    from hetu_tpu.serve import (DisaggRouter, ServingEngine,
                                generate_prefill_burst_load)

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        kw = dict(page_size=64, max_seq_len=2048,
                  prompt_buckets=(128, 256, 512, 1024))
        trace = generate_prefill_burst_load(
            17, 24, vocab=cfg.vocab_size, short_len=(64, 192),
            short_new=(32, 64), long_len=(512, 1024), long_new=(4, 8),
            burst_every=6, burst_size=3, mean_gap_s=0.0)
    else:  # CI smoke: tiny shapes, still the full disagg-vs-colocated A/B
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64)
        kw = dict(page_size=8, max_seq_len=64, prompt_buckets=(8, 16, 32))
        trace = generate_prefill_burst_load(
            17, 12, vocab=cfg.vocab_size, short_len=(2, 6),
            short_new=(2, 6), long_len=(20, 30), long_new=(1, 3),
            burst_every=4, burst_size=2, mean_gap_s=0.0)

    set_random_seed(0)
    model = GPT(cfg)
    hist = _obs.get_registry().histogram("hetu_serve_ttft_seconds").labels()

    def drive(roles, slots):
        engines = [ServingEngine(model, role=r, num_slots=s,
                                 queue_depth=len(trace) + 8,
                                 sampling="top_k", top_k=5, seed=11, **kw)
                   for r, s in zip(roles, slots)]
        router = DisaggRouter(engines)
        # warmup: every prefill bucket on EVERY engine (router placement
        # would leave the unchosen replica cold and bill its compiles to
        # the measured window), which also warms the migration path —
        # a prefill-role engine's direct submit migrates via the hook
        for eng in engines:
            for bucket in kw["prompt_buckets"]:
                eng.submit(list(range(1, bucket + 1)), 2)
            router.run_until_idle()
        cum0 = hist.cumulative()
        # the migration tallies are cumulative from construction: delta
        # them past the warmup (its handoffs are not measured traffic),
        # the TTFT-histogram convention applied to the counters
        mig0 = {k: v for k, v in router.stats()["migrations"].items()}
        t0 = time.perf_counter()
        handles = []
        for it in trace:
            handles.append(router.submit(list(it.prompt),
                                         it.max_new_tokens))
            router.step()
        router.run_until_idle(max_steps=10**7)
        dt = time.perf_counter() - t0
        done = [h for h in handles if h.status == "completed"]
        decode_tokens = sum(max(len(h.tokens) - 1, 0) for h in done)
        stats = router.stats()
        stats["migrations"] = {k: v - mig0[k]
                               for k, v in stats["migrations"].items()}
        return (decode_tokens / dt if dt > 0 else 0.0,
                _hist_quantile(cum0, hist.cumulative(), 0.99),
                len(done), stats)

    # equal chips: the decode worker dedicates the HBM a colocated chip
    # must reserve for prefill activations to wider decode batching
    disagg_tps, d99, done, dstats = drive(
        ["prefill", "decode"], [4, 8] if not on_tpu else [8, 16])
    coloc_tps, c99, cdone, _ = drive(
        ["colocated", "colocated"], [4, 4] if not on_tpu else [8, 8])
    return _line(
        "serve_disagg_decode_tokens_per_sec", disagg_tps, "tokens/s",
        disagg_tps / coloc_tps if coloc_tps > 0 else 1.0,
        ttft_p99_s=_q_or_none(d99),
        colocated_tokens_per_sec=round(coloc_tps, 2),
        colocated_ttft_p99_s=_q_or_none(c99),
        requests=len(trace), completed=done, colocated_completed=cdone,
        migrations=dstats["migrations"],
        baseline_note="vs_baseline = disagg/colocated decode tokens/s on "
                      "the same seeded prefill-burst trace; in-process "
                      "workers TIMESHARE this one device, so the ratio "
                      "isolates the scheduling effect (prefill bursts no "
                      "longer preempt decode) — an N-chip deployment "
                      "multiplies it by its parallelism",
        device=kind, timing="wall-trace", spread=None)


def bench_serve_tenants(on_tpu, kind, peak):
    """``--mode serve --tenants``: the seeded FLOOD A/B — an adversarial
    multi-tenant mix (one batch-class tenant flooding heavy decode
    budgets over a latency-class victim) through a 2-replica fleet with
    the WFQ front door, quotas, and scoped shedding engaged, against the
    victim's OWN arrivals alone on the same fleet.  One JSON line;
    ``vs_baseline`` = victim TTFT p99 under flood / without flood (the
    isolation ratio — 1.0 is perfect isolation, the acceptance bar is
    <1.1), with the shed/quota attribution alongside (the sheds must
    land on the flooder).  Rides the same rc=3 preflight as every serve
    round."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.models import GPT, GPTConfig
    from hetu_tpu.serve import (FleetRouter, ServingEngine, Tenant,
                                TenantPolicy, TokenBucket,
                                generate_multitenant_load)

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        kw = dict(num_slots=8, page_size=64, max_seq_len=2048,
                  prompt_buckets=(128, 256, 512, 1024))
        trace = generate_multitenant_load(
            17, 32, vocab=cfg.vocab_size, mean_gap_s=0.0, tenants=[
                {"id": "flood", "share": 0.8, "prompt_len": (64, 512),
                 "max_new": (32, 64)},
                {"id": "victim", "share": 0.2, "prompt_len": (64, 256),
                 "max_new": (8, 16)}])
        flood_bucket = TokenBucket(capacity=2048.0, refill_per_s=512.0)
    else:  # CI smoke: tiny shapes, still the full flood-vs-quiet A/B
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64)
        kw = dict(num_slots=4, page_size=8, max_seq_len=64,
                  prompt_buckets=(8, 16, 32))
        trace = generate_multitenant_load(
            17, 16, vocab=cfg.vocab_size, mean_gap_s=0.0, tenants=[
                {"id": "flood", "share": 0.8, "prompt_len": (2, 12),
                 "max_new": (8, 16)},
                {"id": "victim", "share": 0.2, "prompt_len": (2, 8),
                 "max_new": (2, 4)}])
        flood_bucket = TokenBucket(capacity=128.0, refill_per_s=64.0)

    set_random_seed(0)
    model = GPT(cfg)

    def drive(items, *, quota):
        # ONE policy shared by both replicas: the flooder's token bucket
        # is a fleet-wide contract, not a per-replica loophole
        policy = TenantPolicy()
        policy.register(Tenant(id="victim", klass="latency", weight=4.0))
        policy.register(Tenant(id="flood", klass="batch", weight=1.0),
                        quota=quota)
        engines = [ServingEngine(model, queue_depth=len(items) + 8,
                                 sampling="top_k", top_k=5, seed=11,
                                 tenants=policy, **kw)
                   for _ in range(2)]
        router = FleetRouter(engines)
        # warmup: compile every prefill bucket on every replica outside
        # the measured window (the _serve_run convention; default-tenant
        # traffic, so no quota charge)
        for eng in engines:
            for bucket in kw["prompt_buckets"]:
                eng.submit(list(range(1, bucket + 1)), 2)
            eng.run_until_idle()
        handles = []
        for it in items:
            handles.append((it, router.submit(list(it.prompt),
                                              it.max_new_tokens,
                                              tenant=it.tenant)))
            router.step()
        router.run_until_idle(max_steps=10**7)
        return handles

    def victim_p99(handles):
        ttfts = sorted(h.ttft_s for it, h in handles
                       if it.tenant == "victim"
                       and h.status == "completed"
                       and h.ttft_s is not None)
        if not ttfts:
            return None
        return ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))]

    flood_handles = drive(trace, quota=flood_bucket)
    quiet_handles = drive([it for it in trace if it.tenant == "victim"],
                          quota=None)
    p99_flood = victim_p99(flood_handles)
    p99_quiet = victim_p99(quiet_handles)
    rejected = [(it, h) for it, h in flood_handles
                if h.status == "rejected"]
    shed_by_tenant: dict = {}
    for it, h in rejected:
        shed_by_tenant[it.tenant] = shed_by_tenant.get(it.tenant, 0) + 1
    return _line(
        "serve_tenant_victim_ttft_p99_s",
        p99_flood if p99_flood is not None else 0.0, "s",
        (p99_flood / p99_quiet
         if p99_flood is not None and p99_quiet else 1.0),
        noflood_victim_ttft_p99_s=_q_or_none(p99_quiet),
        requests=len(trace),
        completed=sum(1 for _, h in flood_handles
                      if h.status == "completed"),
        victim_completed=sum(1 for it, h in flood_handles
                             if it.tenant == "victim"
                             and h.status == "completed"),
        sheds_by_tenant=shed_by_tenant,
        quota_rejections=sum(1 for _, h in rejected
                             if h.shed_reason == "quota"),
        baseline_note="vs_baseline = victim TTFT p99 with the flood / "
                      "without it on the same seeded arrivals — 1.0 is "
                      "perfect tenant isolation (acceptance bar <1.1); "
                      "sheds_by_tenant must load on the flooder",
        device=kind, timing="wall-trace", spread=None)


def bench_plan(on_tpu, kind, peak):
    """``--mode plan``: the unified deployment planner's chosen serving
    config against the hand-tuned stock default on the same seeded
    trace.  The planner is fed by ``fit_calibration`` (named defaults
    fill an empty history) and emits one signed Plan; both arms run the
    SAME workload on injected zero clocks and the headline is the
    deterministic virtual-time decode tokens per router tick —
    ``vs_baseline`` = planner / default, with the plan's sha256 and
    one-line description in the artifact so the decision is
    bitwise-replayable from the journal.  Rides the same rc=3 preflight
    as every serve round."""
    from hetu_tpu.core import set_random_seed
    from hetu_tpu.models import GPT, GPTConfig
    from hetu_tpu.obs import calibration as _calibration
    from hetu_tpu.plan import DeploymentSpec, build_fleet, plan_deployment
    from hetu_tpu.serve import FleetRouter, ServingEngine, generate_load

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        spec = DeploymentSpec(
            model_sig="gpt-bench", n_layers=8, hidden_size=1024,
            seq_len=2048, vocab_size=32000, global_batch=8,
            n_devices=2, serve_devices=2, hbm_bytes=16e9,
            peak_flops=max(peak, 1e12), device_kind=kind,
            requests_per_s=4.0, prompt_p50=128, prompt_p99=1024,
            decode_len=48, slots_per_replica=8, page_size=64)
        trace = generate_load(17, 24, vocab=cfg.vocab_size,
                              prompt_len=(64, 1024), max_new=(32, 64),
                              mean_gap_s=0.0)
    else:  # CI smoke: tiny shapes, still the full planner-vs-default A/B
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64)
        spec = DeploymentSpec(
            model_sig="gpt-ci", n_layers=2, hidden_size=32, seq_len=64,
            vocab_size=97, global_batch=8, n_devices=2, serve_devices=2,
            hbm_bytes=2e9, peak_flops=max(peak, 1e12), device_kind=kind,
            requests_per_s=4.0, prompt_p50=8, prompt_p99=16,
            decode_len=6, slots_per_replica=8, page_size=8)
        trace = generate_load(17, 48, vocab=cfg.vocab_size,
                              prompt_len=(2, 12), max_new=(2, 6),
                              mean_gap_s=0.0)

    # calibration plane in, named defaults for whatever has no history
    # yet — a fresh checkout still plans deterministically
    store = _calibration.get_store()
    if store is None:
        store = _calibration.ProfileStore(clock=lambda: 0.0)
    cal = _calibration.fit_calibration(store, model_sig=spec.model_sig,
                                       device_kind=kind, defaults=True)
    plan = plan_deployment(spec, calibration=cal)

    set_random_seed(0)
    model = GPT(cfg)

    def drive(router):
        # warmup: compile every prefill bucket on every replica outside
        # the measured window (the _serve_run convention)
        for eng in router.engines:
            for bucket in eng.batcher.prompt_buckets:
                eng.submit(list(range(1, bucket + 1)), 2)
            eng.run_until_idle()
        handles = [router.submit(list(it.prompt), it.max_new_tokens)
                   for it in trace]
        ticks = 0
        while not router.idle and ticks < 10**7:
            router.step()
            ticks += 1
        done = [h for h in handles if h.status == "completed"]
        tokens = sum(max(len(h.tokens) - 1, 0) for h in done)
        return (tokens / max(ticks, 1), tokens, ticks, len(done))

    planned = build_fleet(model, plan, clock=lambda: 0.0,
                          queue_depth=len(trace) + 8)
    stock = FleetRouter([ServingEngine(model, clock=lambda: 0.0,
                                       queue_depth=len(trace) + 8)])
    p_tpt, p_tokens, p_ticks, p_done = drive(planned)
    d_tpt, d_tokens, d_ticks, d_done = drive(stock)
    return _line(
        "plan_decode_tokens_per_tick", p_tpt, "tokens/tick",
        p_tpt / d_tpt if d_tpt > 0 else 1.0,
        plan_sha256=plan.sha256, plan=plan.describe(),
        calibration_fallbacks=len(cal.fallbacks),
        planner_ticks=p_ticks, planner_tokens=p_tokens,
        default_tokens_per_tick=round(d_tpt, 4),
        default_ticks=d_ticks, default_tokens=d_tokens,
        requests=len(trace), completed=p_done, default_completed=d_done,
        baseline_note="vs_baseline = planner/default decode tokens per "
                      "virtual router tick on the same seeded trace "
                      "(deterministic: injected zero clocks, greedy "
                      "sampling) — the acceptance bar is >1.0 on at "
                      "least one measured axis",
        device=kind, timing="virtual-ticks", spread=None)


def bench_broker(on_tpu, kind, peak):
    """``--mode broker``: one seeded diurnal day, brokered vs BOTH
    static splits.  The brokered arm starts train-heavy (world 4, one
    replica) and lets the :class:`~hetu_tpu.broker.CapacityBroker`
    lease chips to the fleet on sustained SLO burn; split A is the same
    day with the broker disabled (train-heavy forever), split B is the
    serve-heavy split (world 3, two replicas) the broker would reach at
    peak, held all day.  All three run the identical trace on one
    virtual clock, so the headline is deterministic: ``vs_baseline`` is
    the JOINT dominance margin ``min(brokered_steps / B_steps,
    A_violations / brokered_violations)`` — > 1.0 means the broker beat
    the serve-heavy split on training goodput AND the train-heavy split
    on SLO violations at once, which neither static split can do.
    Rides the same rc=3 preflight as every mode."""
    import tempfile

    from hetu_tpu.broker.episode import run_broker_episode

    with tempfile.TemporaryDirectory() as root:
        brokered = run_broker_episode(os.path.join(root, "brokered"),
                                      seed=0, brokered=True)
        split_a = run_broker_episode(os.path.join(root, "a"), seed=0,
                                     brokered=False, train_world=4,
                                     serve_replicas=1)
        split_b = run_broker_episode(os.path.join(root, "b"), seed=0,
                                     brokered=False, train_world=3,
                                     serve_replicas=2)

    steps_margin = (brokered.goodput / split_b.goodput
                    if split_b.goodput > 0 else float("inf"))
    viol_margin = (split_a.violations / brokered.violations
                   if brokered.violations > 0 else float("inf"))
    dominance = min(steps_margin, viol_margin)
    kinds = [e["kind"] for e in brokered["lease_events"]]
    return _line(
        "broker_joint_dominance", dominance, "x", dominance,
        brokered_train_steps=brokered.goodput,
        brokered_violations=brokered.violations,
        split_a_train_steps=split_a.goodput,
        split_a_violations=split_a.violations,
        split_b_train_steps=split_b.goodput,
        split_b_violations=split_b.violations,
        steps_vs_serve_heavy=round(steps_margin, 4),
        violations_vs_train_heavy=round(viol_margin, 4),
        grants=kinds.count("lease_grant"),
        reclaims=kinds.count("lease_reclaim"),
        final_world=brokered["final_world"],
        leases_returned=all(
            lease["state"] == "returned"
            for lease in brokered["leases"]),
        # the episode knobs ARE the calibration record: re-run with
        # these and the journal replays bitwise
        seed=0, n_requests=96, peak_gap_s=0.033, tick_s=0.05,
        chip_seconds_per_step=2.0, overnight_ticks=60,
        overnight_tick_s=2.0, min_train_world=3,
        baseline_note="vs_baseline = min(brokered/serve-heavy train "
                      "steps, train-heavy/brokered SLO violations) on "
                      "the same seeded diurnal trace (deterministic: "
                      "one virtual clock, journaled leases) — the "
                      "acceptance bar is > 1.0, i.e. the broker "
                      "jointly dominates both static splits",
        device=kind, timing="virtual-ticks", spread=None)


CONFIGS = [
    ("resnet", bench_resnet),
    ("ctr", bench_ctr),
    ("moe", bench_moe),
    ("autogpt", bench_autogpt),
    ("bert512", bench_bert_long),
    ("bert", bench_bert_headline),  # headline LAST
]

_TRANSIENT = ("rpc", "deadline", "unavailable", "connection", "stream")


PREFLIGHT_RC = 3  # exit code: the device tunnel failed preflight — the
# run produced NO results (this is a harness failure, not a regression)


def _preflight_fail(reason: str, *, hard: bool = False):
    """Named diagnosis on STDERR, exit ``PREFLIGHT_RC``, and — critically
    — NOTHING on stdout: rounds 4-5 emitted a ``backend_unreachable``
    metric line that the driver recorded as if it were a benchmark
    result (BENCH_r04/r05.json).  A dead tunnel must read as a failed
    preflight, never as a round of numbers.  ``hard`` uses ``os._exit``
    for the hung-probe case (a wedged C client cannot be joined)."""
    import os
    print(f"bench: PREFLIGHT FAILED — device tunnel unusable\n"
          f"bench: diagnosis: {reason}\n"
          f"bench: no metric lines were emitted; exit code {PREFLIGHT_RC} "
          f"means 'no results this run', not a perf regression",
          file=sys.stderr)
    sys.stderr.flush()
    if hard:
        os._exit(PREFLIGHT_RC)
    sys.exit(PREFLIGHT_RC)


def _require_backend_alive(timeout_s: float = 240.0, probe=None,
                           retry_wait: float = 5.0):
    """Preflight: prove the device backend answers a trivial program
    BEFORE any benchmark work, failing fast with a named diagnosis
    (stderr + rc=3, see :func:`_preflight_fail`) instead of hanging on
    the first dispatch or emitting a bogus round.  The tunneled chip's
    relay can die (r04: gone for 8+ hours; a hung make_c_api_client
    blocks in C and cannot be interrupted), so the probe runs on a
    daemon thread and a watchdog hard-exits."""
    import threading

    def default_probe():
        x = jnp.ones((8, 8))
        float((x @ x).sum())

    probe = probe or default_probe
    for attempt in (0, 1):
        settled = threading.Event()
        err = []

        def run():
            try:
                probe()
            except Exception as e:  # deterministic failure: report IT
                err.append(f"{type(e).__name__}: {e}")
            settled.set()

        threading.Thread(target=run, daemon=True).start()
        if not settled.wait(timeout_s):
            _preflight_fail(
                f"device backend did not answer a trivial program within "
                f"{timeout_s:.0f}s (dead tunnel relay / hung C client)",
                hard=True)
        if not err:
            return
        # transient tunnel/RPC blips get ONE retry, matching the
        # per-config retry policy in main(); anything else is terminal
        if attempt == 0 and any(s in err[0].lower() for s in _TRANSIENT):
            time.sleep(retry_wait)
            continue
        _preflight_fail(
            f"device backend failed a trivial program: {err[0][:400]}")


def main():
    args = sys.argv[1:]
    mode = "train"
    if "--mode" in args:
        i = args.index("--mode")
        if i + 1 >= len(args):
            sys.exit("bench: --mode needs a value (train | serve)")
        mode = args[i + 1]
        del args[i:i + 2]
    if mode not in ("train", "serve", "ctr", "plan", "broker"):
        sys.exit(f"bench: unknown mode {mode!r}; one of 'train', 'serve', "
                 f"'ctr', 'plan', 'broker'")
    if mode == "broker":
        if args:
            sys.exit(f"bench: --mode broker takes no config names, "
                     f"got {args}")
        # same rc=3 preflight: a dead tunnel must never record a bogus
        # dominance round
        _require_backend_alive()
        on_tpu, kind, peak = _env()
        try:
            bench_broker(on_tpu, kind, peak)
        except Exception:
            traceback.print_exc()
            sys.exit(1)
        return
    if mode == "plan":
        if args:
            sys.exit(f"bench: --mode plan takes no config names, "
                     f"got {args}")
        # behind the same rc=3 preflight as every mode: a dead tunnel
        # must never record a bogus A/B round (or planner baseline)
        _require_backend_alive()
        on_tpu, kind, peak = _env()
        try:
            bench_plan(on_tpu, kind, peak)
        except Exception:
            traceback.print_exc()
            sys.exit(1)
        return
    if mode == "ctr":
        embedding = "host"
        if "--embedding" in args:
            i = args.index("--embedding")
            if i + 1 >= len(args):
                sys.exit("bench: --embedding needs a value (host | tiered)")
            embedding = args[i + 1]
            del args[i:i + 2]
        if embedding not in ("host", "tiered"):
            sys.exit(f"bench: unknown embedding {embedding!r}; one of "
                     f"'host', 'tiered'")
        storage = "f32"
        if "--storage" in args:
            i = args.index("--storage")
            if i + 1 >= len(args):
                sys.exit("bench: --storage needs a value (f32 | int8)")
            storage = args[i + 1]
            del args[i:i + 2]
        if storage not in ("f32", "int8"):
            sys.exit(f"bench: unknown storage {storage!r}; one of 'f32', "
                     f"'int8'")
        if args:
            sys.exit(f"bench: --mode ctr takes no config names, got {args}")
        # behind the same rc=3 preflight as every mode: a dead tunnel must
        # never record a bogus A/B round (or calibration baseline)
        _require_backend_alive()
        on_tpu, kind, peak = _env()
        try:
            if embedding == "tiered":
                bench_ctr_tiered(on_tpu, kind, peak, storage=storage)
            else:
                bench_ctr(on_tpu, kind, peak)
        except Exception:
            traceback.print_exc()
            sys.exit(1)
        return
    if mode == "serve":
        replicas = None
        if "--replicas" in args:
            i = args.index("--replicas")
            if i + 1 >= len(args):
                sys.exit("bench: --replicas needs a count")
            try:
                replicas = int(args[i + 1])
            except ValueError:
                sys.exit(f"bench: --replicas needs an integer, "
                         f"got {args[i + 1]!r}")
            if replicas < 1:
                sys.exit(f"bench: --replicas must be >= 1, got {replicas}")
            del args[i:i + 2]
        prefix_share = "--prefix-share" in args
        if prefix_share:
            args.remove("--prefix-share")
        if prefix_share and replicas is None:
            replicas = 2  # sharing is a fleet feature; A/B needs a fleet
        disagg = "--disagg" in args
        if disagg:
            args.remove("--disagg")
        if disagg and (replicas is not None or prefix_share):
            sys.exit("bench: --disagg runs its own 1-prefill + 1-decode "
                     "vs 2-colocated A/B; drop --replicas/--prefix-share")
        tenants = "--tenants" in args
        if tenants:
            args.remove("--tenants")
        if tenants and (disagg or replicas is not None or prefix_share):
            sys.exit("bench: --tenants runs its own 2-replica flood A/B; "
                     "drop --disagg/--replicas/--prefix-share")
        chaos = "--chaos" in args
        if chaos:
            args.remove("--chaos")
        if chaos and (tenants or disagg or replicas is not None
                      or prefix_share):
            sys.exit("bench: --chaos runs its own 3-replica crash-vs-clean "
                     "A/B; drop --tenants/--disagg/--replicas/"
                     "--prefix-share")
        if args:
            sys.exit(f"bench: --mode serve takes no config names, "
                     f"got {args}")
        _require_backend_alive()
        on_tpu, kind, peak = _env()
        try:
            if chaos:
                bench_serve_chaos(on_tpu, kind, peak)
            elif tenants:
                bench_serve_tenants(on_tpu, kind, peak)
            elif disagg:
                bench_serve_disagg(on_tpu, kind, peak)
            elif replicas is not None:
                bench_serve_fleet(on_tpu, kind, peak, replicas=replicas,
                                  prefix_share=prefix_share)
            else:
                bench_serve(on_tpu, kind, peak)
        except Exception:
            traceback.print_exc()
            sys.exit(1)
        return
    names = {name for name, _ in CONFIGS}
    unknown = set(args) - names
    if unknown:  # usage errors need no backend: fail instantly
        sys.exit(f"bench: unknown config(s) {sorted(unknown)}; "
                 f"choose from {sorted(names)}")
    _require_backend_alive()
    only = set(args) or names
    on_tpu, kind, peak = _env()
    done = set()
    for name, fn in CONFIGS:
        if name not in only:
            continue
        if name == "bert512" and not on_tpu:
            # off-TPU the long-seq config collapses to the same smoke
            # workload as the headline — don't emit a duplicate metric
            print("bench[bert512]: skipped off-TPU (same smoke shape as "
                  "headline)", file=sys.stderr)
            continue
        try:
            fn(on_tpu, kind, peak)
            done.add(name)
        except Exception as e:  # one config must not cost the others
            traceback.print_exc()
            # retry only known-transient tunnel/compile-RPC failures, not
            # arbitrary errors (a deterministic bug would just repeat)
            if any(s in str(e).lower() for s in _TRANSIENT):
                print(f"bench[{name}]: transient failure, retrying once",
                      file=sys.stderr)
                try:
                    fn(on_tpu, kind, peak)
                    done.add(name)
                except Exception:
                    traceback.print_exc()
    # the documented contract is final-line = headline BERT metric: a missing
    # headline must be an ERROR, not a silent fall-through to whatever
    # printed last
    if "bert" in only and "bert" not in done:
        print("bench: headline bert config FAILED", file=sys.stderr)
        sys.exit(1)
    if not done:
        sys.exit(1)


if __name__ == "__main__":
    main()
